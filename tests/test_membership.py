"""Elastic membership: join (``Cluster.add_node`` + join epoch) and
decommission (``Cluster.decommission`` hand-off) under load.

Covers ISSUE 8's membership-change contract: a joining node bootstraps its
ranges from live peers (fence sync point + data fetch through the PR-1/2
journal/bootstrap machinery) and serves reads only after the fetch lands; a
leaving node hands off and is removed from every shard without data loss; a
joiner crashing mid-bootstrap recovers through the restart catch-up ladder;
and the elastic burn is deterministic with the flight recorder on vs off
(zero observer effect extends to the membership plane)."""
import pytest

from dataclasses import replace

from cassandra_accord_tpu.config import LocalConfig
from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.harness.nemesis import MembershipNemesis
from cassandra_accord_tpu.harness.topology_randomizer import TopologyRandomizer
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def make_cluster(nodes=(1, 2, 3), seed=5, **kw):
    topo = Topology(1, [Shard(Range(k(0), k(1000)), list(nodes))])
    return Cluster(topo, seed=seed, journal=True, progress_log=True,
                   progress_poll_s=0.2, **kw)


def write(cluster, node_id, appends):
    return cluster.nodes[node_id].coordinate(
        list_txn([], {k(key): v for key, v in appends.items()}))


# ---------------------------------------------------------------------------
# Cluster.add_node + join epoch
# ---------------------------------------------------------------------------

def test_join_bootstraps_and_serves_reads_only_after_fetch():
    """A mid-run-spawned node joins a shard: its adopted range is
    pending-bootstrap (reads refused there; peers/union serve) until the
    fetch lands, after which it holds the pre-join data and serves reads."""
    cluster = make_cluster(seed=7)
    w = write(cluster, 1, {10: "pre", 700: "pre2"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()

    node4 = cluster.add_node(4)
    assert 4 in cluster.nodes and cluster.stats.get("node_joins") == 1
    # not yet a member: owns nothing, no bootstrap launched
    assert all(not cs.pending_bootstrap
               for cs in node4.command_stores.all_stores())

    cluster.update_topology(Topology(2, [
        Shard(Range(k(0), k(1000)), [1, 2, 4])]))
    # the join epoch's adoption diff marks the range pending at node 4
    cluster.run_until(lambda: any(
        cs.pending_bootstrap for cs in node4.command_stores.all_stores()),
        max_tasks=200_000)
    store4 = node4.command_stores.all_stores()[0]
    assert store4.pending_bootstrap, "join must enter the bootstrap ladder"
    # reads DURING the joiner's bootstrap still succeed (peers serve)
    r = cluster.nodes[2].coordinate(list_txn([k(10)], {}))
    assert cluster.run_until(r.is_done, max_tasks=2_000_000)
    assert r.value.reads[k(10)] == ("pre",)
    cluster.run_until_idle()
    # bootstrap complete: fetched pre-join data, serves afterwards
    assert not store4.pending_bootstrap
    assert cluster.stores[4].get(k(10)) == ("pre",)
    assert cluster.stores[4].get(k(700)) == ("pre2",)
    e = store4.redundant_before.entry(k(10).to_routing())
    assert e is not None and e.bootstrapped_at is not None
    w2 = write(cluster, 4, {10: "post"})
    assert cluster.run_until(w2.is_done)
    cluster.run_until_idle()
    assert cluster.stores[4].get(k(10)) == ("pre", "post")


def test_join_while_loaded_no_write_loss():
    """Writes in flight across the join epoch all survive into the
    post-join replica set, consistently."""
    cluster = make_cluster(seed=11)
    results = [write(cluster, 1 + (i % 3), {5: f"a{i}"}) for i in range(4)]
    cluster.add_node(4)
    cluster.update_topology(Topology(2, [
        Shard(Range(k(0), k(1000)), [1, 2, 4])]))
    results += [write(cluster, 1 + (i % 3), {5: f"b{i}"}) for i in range(4)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results),
                             max_tasks=5_000_000)
    cluster.run_until_idle()
    lists = {cluster.stores[n].get(k(5)) for n in (1, 2, 4)}
    assert len(lists) == 1, lists
    assert sorted(lists.pop()) == sorted(
        [f"a{i}" for i in range(4)] + [f"b{i}" for i in range(4)])


def test_join_crash_mid_bootstrap_recovers():
    """A joiner crashing MID-BOOTSTRAP re-enters the catch-up ladder at
    restart (the crash carries pending_bootstrap as restart debt) and still
    converges with the pre-join data."""
    cluster = make_cluster(seed=13)
    w = write(cluster, 1, {10: "pre"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()
    node4 = cluster.add_node(4)
    cluster.update_topology(Topology(2, [
        Shard(Range(k(0), k(1000)), [1, 2, 4])]))
    cluster.run_until(lambda: any(
        cs.pending_bootstrap for cs in node4.command_stores.all_stores()),
        max_tasks=200_000)
    assert any(cs.pending_bootstrap
               for cs in node4.command_stores.all_stores())
    cluster.crash(4)
    cluster.run_for(2)
    cluster.restart(4)
    cluster.run_for(60)
    assert cluster.stores[4].get(k(10)) == ("pre",)
    store4 = cluster.nodes[4].command_stores.all_stores()[0]
    assert not store4.pending_bootstrap


# ---------------------------------------------------------------------------
# Cluster.decommission
# ---------------------------------------------------------------------------

def test_decommission_hands_off_without_data_loss():
    """The leaver is removed from every shard in one epoch; replacements
    bootstrap its data; the drained process stays live serving old epochs."""
    cluster = make_cluster(nodes=(1, 2, 3), seed=17, extra_nodes=[4])
    w = write(cluster, 1, {10: "v1", 900: "v2"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()
    topo = cluster.decommission(3)
    assert topo is not None and 3 not in topo.nodes()
    assert 3 in cluster.decommissioned and 3 in cluster.nodes
    assert cluster.stats.get("node_decommissions") == 1
    cluster.run_until_idle()
    # the replacement (node 4, the only non-member) bootstrapped the data
    assert cluster.stores[4].get(k(10)) == ("v1",)
    assert cluster.stores[4].get(k(900)) == ("v2",)
    # post-handoff traffic converges on the new replica set
    w2 = write(cluster, 1, {10: "v3"})
    assert cluster.run_until(w2.is_done)
    cluster.run_until_idle()
    lists = {cluster.stores[n].get(k(10)) for n in (1, 2, 4)}
    assert lists == {("v1", "v3")}, lists


def test_decommission_refuses_without_replacement():
    """Every live node already replicates the shard: no hand-off target —
    decommission returns None and changes nothing."""
    cluster = make_cluster(nodes=(1, 2, 3), seed=19)
    epoch = cluster.topologies[-1].epoch
    assert cluster.decommission(2) is None
    assert cluster.topologies[-1].epoch == epoch
    assert 2 not in cluster.decommissioned


# ---------------------------------------------------------------------------
# TopologyRandomizer elastic mutations + MembershipNemesis
# ---------------------------------------------------------------------------

def test_randomizer_join_spawns_from_pool_and_leave_drains():
    cluster = make_cluster(nodes=(1, 2, 3), seed=23, extra_nodes=[4])
    cluster.run_until_idle()
    randomizer = TopologyRandomizer(cluster, RandomSource(3), elastic=True,
                                    spawn_pool=[5, 6])
    current = cluster.topologies[-1]
    new_shards = randomizer._join(list(current.shards), current)
    assert new_shards is not None
    members = {n for s in new_shards for n in s.nodes}
    newcomer = members - current.nodes()
    assert len(newcomer) == 1
    # an existing live non-member (4) is preferred over spawning
    assert newcomer == {4}
    cluster.update_topology(Topology(current.epoch + 1, new_shards))
    cluster.run_until_idle()

    # leave: with 4 members and rf 3 someone can be spared
    current = cluster.topologies[-1]
    out = randomizer._leave(list(current.shards), current)
    if out is not None:
        after = {n for s in out for n in s.nodes}
        assert len(current.nodes() - after) <= 1


def test_membership_nemesis_cycles_under_load():
    """Seeded join/decommission cycles on a burn: members change, every op
    resolves, final replica sets agree (run_burn's end checks)."""
    cfg = replace(LocalConfig(), membership_interval_s=3.0)
    result = run_burn(1, ops=80, concurrency=10, chaos=True,
                      allow_failures=True, topology_churn=True,
                      elastic_membership=True, durability=True, journal=True,
                      node_config=cfg, stall_watchdog_s=120.0,
                      max_tasks=40_000_000)
    assert result.resolved == 80
    assert result.joins >= 1, result
    assert result.leaves >= 1, result


def test_elastic_burn_deterministic_and_recorder_invisible():
    """Same-seed elastic burn twice: byte-identical message traces; and the
    flight recorder on vs off stays byte-identical too (zero observer effect
    extends to the membership plane)."""
    from cassandra_accord_tpu.observe import FlightRecorder
    cfg = replace(LocalConfig(), membership_interval_s=3.0)
    kw = dict(ops=60, concurrency=10, chaos=True, allow_failures=True,
              topology_churn=True, elastic_membership=True, durability=True,
              journal=True, node_config=cfg, max_tasks=40_000_000)
    ta, tb, tc = Trace(), Trace(), Trace()
    a = run_burn(2, tracer=ta.hook, **kw)
    b = run_burn(2, tracer=tb.hook, **kw)
    assert diff_traces(ta, tb) is None
    c = run_burn(2, tracer=tc.hook, observer=FlightRecorder(), **kw)
    assert diff_traces(ta, tc) is None, \
        "the flight recorder perturbed an elastic-membership burn"
    assert (a.ops_ok, a.joins, a.leaves, a.sim_micros) == \
           (c.ops_ok, c.joins, c.leaves, c.sim_micros)


def test_elastic_gray_failure_burn():
    """Elastic membership composed with the gray-failure axes (crash-restart
    + pause + disk stall): joins/leaves interleave with kills and every op
    still resolves."""
    cfg = replace(LocalConfig(), membership_interval_s=4.0,
                  restart_interval_s=6.0, pause_interval_s=5.0,
                  disk_stall_interval_s=7.0)
    result = run_burn(4, ops=80, concurrency=10, chaos=True,
                      allow_failures=True, topology_churn=True,
                      elastic_membership=True, durability=True, journal=True,
                      restart_nodes=True, pause_nodes=True, disk_stall=True,
                      node_config=cfg, stall_watchdog_s=150.0,
                      max_tasks=80_000_000)
    assert result.resolved == 80
    assert result.joins + result.leaves >= 1, result


def test_seed8_unknown_epoch_probe_regression():
    """Round-13 find (flushed by the seeds 0-9 x 250-op acceptance matrix
    under --elastic): a replica can learn of a blocked txn through
    deps/inform traffic BEFORE its config service delivers the txn's epoch.
    The progress log then escalated to fetch_data -> check_status_quorum,
    whose direct `precise_epochs(route, epoch, epoch)` call threw
    "epochs [10,10] not all known" and killed the burn.  The fix gates the
    probe on `node.with_epoch(txn_id.epoch)` (FetchData.java's withEpoch
    wrap) — synchronous when the epoch is known, so established
    trajectories are byte-identical.  This is the verbatim crash shape at
    the smallest reproducing op count."""
    rf = 2 + RandomSource(8).next_int(8)   # mirror the burn CLI's seeded rf
    result = run_burn(8, ops=150, concurrency=20, rf=rf, chaos=True,
                      allow_failures=True, topology_churn=True,
                      elastic_membership=True, durability=True, journal=True,
                      delayed_stores=True, clock_drift=True, cache_miss=True,
                      restart_nodes=True, pause_nodes=True, disk_stall=True,
                      audit="strict", max_tasks=200_000_000)
    assert result.resolved == 150
    assert result.joins + result.leaves >= 1, result
