"""Durability watermarks, GC bounds, truncation, and the durability rounds.

Parity targets: RedundantBefore.java:49-529, DurableBefore.java, Cleanup.java,
SetShardDurable/SetGloballyDurable/QueryDurableBefore, CoordinateShardDurable /
CoordinateGloballyDurable, CoordinateDurabilityScheduling.java:78-350.
"""
from cassandra_accord_tpu.coordinate.durability import (
    coordinate_globally_durable, coordinate_shard_durable)
from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig
from cassandra_accord_tpu.impl.durability_scheduling import (
    CoordinateDurabilityScheduling, _split)
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.local.durability import (
    Cleanup, DurableBefore, RedundantBefore, should_cleanup)
from cassandra_accord_tpu.local.status import Durability, SaveStatus
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import TxnId, TxnKind, Domain
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId(epoch=1, hlc=hlc, node=node, kind=kind, domain=Domain.KEY)


def make_cluster(seed=1, nodes=(1, 2, 3), shards=None, **kw):
    if shards is None:
        shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    return Cluster(Topology(1, shards), seed=seed, **kw)


def submit_write(cluster, node_id, appends):
    txn = list_txn([], {k(key): v for key, v in appends.items()})
    return cluster.nodes[node_id].coordinate(txn)


# ---------------------------------------------------------------------------
# unit: the range maps
# ---------------------------------------------------------------------------

def test_redundant_before_bounds():
    rb = RedundantBefore.of(Ranges.of(Range(k(0), k(100))),
                            locally_applied_before=tid(50))
    assert rb.locally_redundant_before(k(10).to_routing()) == tid(50)
    assert rb.locally_redundant_before(k(500).to_routing()) is None
    assert rb.is_locally_redundant(tid(10), Ranges.of(Range(k(0), k(100))))
    assert not rb.is_locally_redundant(tid(60), Ranges.of(Range(k(0), k(100))))
    # partial coverage: not redundant (range extends past the bound's range)
    assert not rb.is_locally_redundant(tid(10), Ranges.of(Range(k(0), k(200))))


def test_redundant_before_merge_takes_max():
    a = RedundantBefore.of(Ranges.of(Range(k(0), k(100))), locally_applied_before=tid(50))
    b = RedundantBefore.of(Ranges.of(Range(k(50), k(200))), locally_applied_before=tid(80))
    m = a.merge(b)
    assert m.locally_redundant_before(k(10).to_routing()) == tid(50)
    assert m.locally_redundant_before(k(60).to_routing()) == tid(80)
    assert m.locally_redundant_before(k(150).to_routing()) == tid(80)


def test_durable_before_levels_and_min_merge():
    db = DurableBefore.of(Ranges.of(Range(k(0), k(100))),
                          majority_before=tid(50), universal_before=tid(20))
    assert db.durability_of(tid(10), k(5).to_routing()) is Durability.UNIVERSAL
    assert db.durability_of(tid(30), k(5).to_routing()) is Durability.MAJORITY
    assert db.durability_of(tid(90), k(5).to_routing()) is Durability.NOT_DURABLE
    other = DurableBefore.of(Ranges.of(Range(k(0), k(100))), majority_before=tid(30))
    agreed = db.merge_min(other)
    assert agreed.entry(k(5).to_routing()).majority_before == tid(30)


def test_cleanup_lattice():
    class Cmd:
        def __init__(self, txn_id, save_status, route):
            self.txn_id = txn_id
            self.save_status = save_status
            self.route = route

    from cassandra_accord_tpu.primitives.route import Route
    route = Route.for_ranges(k(0).to_routing(), Ranges.of(Range(k(0), k(100))))
    rb = RedundantBefore.of(Ranges.of(Range(k(0), k(100))),
                            locally_applied_before=tid(100))
    db_not = DurableBefore.EMPTY
    db_maj = DurableBefore.of(Ranges.of(Range(k(0), k(100))), majority_before=tid(100))
    db_uni = DurableBefore.of(Ranges.of(Range(k(0), k(100))),
                              majority_before=tid(100), universal_before=tid(100))
    applied = Cmd(tid(10), SaveStatus.APPLIED, route)
    assert should_cleanup(applied, rb, db_not) is Cleanup.TRUNCATE_WITH_OUTCOME
    assert should_cleanup(applied, rb, db_maj) is Cleanup.TRUNCATE
    assert should_cleanup(applied, rb, db_uni) is Cleanup.ERASE
    # not locally redundant -> NO
    assert should_cleanup(Cmd(tid(200), SaveStatus.APPLIED, route), rb, db_uni) is Cleanup.NO
    # still executing -> NO
    assert should_cleanup(Cmd(tid(10), SaveStatus.STABLE, route), rb, db_uni) is Cleanup.NO


def test_split_helper():
    pieces = _split(Range(k(0), k(100)), 4)
    assert len(pieces) == 4
    assert pieces[0].start == k(0) and pieces[-1].end == k(100)
    for a, b in zip(pieces, pieces[1:]):
        assert a.end == b.start


# ---------------------------------------------------------------------------
# integration: rounds on the simulated cluster
# ---------------------------------------------------------------------------

def test_shard_durable_round_advances_watermarks_and_truncates():
    cluster = make_cluster(seed=3)
    results = [submit_write(cluster, 1 + (i % 3), {i * 10: f"v{i}"}) for i in range(6)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()

    res = coordinate_shard_durable(cluster.nodes[1], Ranges.of(Range(k(0), k(1000))))
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()

    # every replica advanced DurableBefore (the all-replica round proves
    # universal durability directly) and GC'd the applied writes: erased
    # outright or at least truncated
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            if not store.current_ranges():
                continue
            e = store.durable_before.entry(k(10).to_routing())
            assert e is not None and e.majority_before is not None, \
                f"node {n}: no durability watermark"
            assert e.universal_before is not None, \
                f"node {n}: all-replica round did not prove universal"
            live = [c for c in store.commands.values()
                    if c.save_status is SaveStatus.APPLIED
                    and c.txn_id.kind is TxnKind.WRITE]
            assert not live, f"node {n}: applied writes never cleaned up: {live}"


def test_globally_durable_round_upgrades_to_universal():
    cluster = make_cluster(seed=5)
    results = [submit_write(cluster, 1, {7: "a", 13: "b"})]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    res = coordinate_shard_durable(cluster.nodes[1], Ranges.of(Range(k(0), k(1000))))
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    res2 = coordinate_globally_durable(cluster.nodes[2])
    assert cluster.run_until(res2.is_done)
    cluster.run_until_idle()
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            if not store.current_ranges():
                continue
            e = store.durable_before.entry(k(7).to_routing())
            assert e is not None and e.universal_before is not None, \
                f"node {n}: universal watermark not disseminated"


def test_new_txns_still_correct_after_gc():
    """Post-GC, new conflicting txns must still serialize correctly even though
    their predecessors were truncated out of the indexes."""
    cluster = make_cluster(seed=7)
    for i in range(4):
        r = submit_write(cluster, 1 + (i % 3), {5: f"pre{i}"})
        assert cluster.run_until(r.is_done)
    cluster.run_until_idle()
    res = coordinate_shard_durable(cluster.nodes[1], Ranges.of(Range(k(0), k(1000))))
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    # now new writes + read on the same key
    for i in range(3):
        r = submit_write(cluster, 1 + (i % 3), {5: f"post{i}"})
        assert cluster.run_until(r.is_done)
    rd = cluster.nodes[2].coordinate(list_txn([k(5)], {}))
    assert cluster.run_until(rd.is_done)
    cluster.run_until_idle()
    got = rd.value.reads[k(5)]
    assert got[-3:] == ("post0", "post1", "post2"), got
    assert got[:4] == ("pre0", "pre1", "pre2", "pre3"), got
    lists = {cluster.stores[n].get(k(5)) for n in cluster.nodes}
    assert len(lists) == 1, lists


def test_durability_scheduling_runs_rounds():
    cluster = make_cluster(seed=11)
    results = [submit_write(cluster, 1, {50: "x"})]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    scheds = []
    for n in cluster.nodes:
        s = CoordinateDurabilityScheduling(cluster.nodes[n], shard_cycle_time_s=0.5,
                                           global_cycle_time_s=1.0)
        s.start()
        scheds.append(s)
    # run simulated time forward; recurring tasks keep the queue non-empty, so
    # step a bounded number of tasks instead of draining
    deadline = cluster.now_micros + 5_000_000
    cluster.run_until(lambda: cluster.now_micros >= deadline, max_tasks=200_000)
    ok = False
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            e = store.durable_before.entry(k(50).to_routing())
            if e is not None and e.majority_before is not None:
                ok = True
    assert ok, "scheduled durability rounds never advanced any watermark"
    for s in scheds:
        s.stop()


# ---------------------------------------------------------------------------
# a replica outside the apply quorum must never lose writes to a concurrent
# durability round (the round requires ALL replicas to ack application before
# broadcasting SetShardDurable; CoordinateShardDurable.java AppliedTracker
# waits shard.rf(), not a quorum)
# ---------------------------------------------------------------------------

class _PartitionNode(LinkConfig):
    """Drops every message to/from ``isolated`` while ``active``."""

    def __init__(self, rng, isolated: int):
        super().__init__(rng)
        self.isolated = isolated
        self.active = False

    def action(self, from_node: int, to_node: int, message=None) -> str:
        if self.active and self.isolated in (from_node, to_node):
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def test_shard_durable_round_does_not_strand_partitioned_replica():
    from cassandra_accord_tpu.utils.random import RandomSource
    link = _PartitionNode(RandomSource(101), isolated=3)
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=13, link_config=link,
                      progress_log=True)

    # partition node 3, then write: the txns apply at the {1,2} quorum only
    link.active = True
    results = [submit_write(cluster, 1, {i: f"v{i}"}) for i in range(4)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results),
                             max_tasks=500_000)

    # a durability round concurrent with the partition MUST NOT advance
    # watermarks: node 3 has not applied, so the all-replica barrier cannot
    # complete (quorum-gated rounds would broadcast here and let peers ERASE
    # outcomes node 3 still needs)
    res = coordinate_shard_durable(cluster.nodes[1], Ranges.of(Range(k(0), k(1000))))
    assert cluster.run_until(res.is_done, max_tasks=500_000)
    assert res.failure is not None, "durability round succeeded under partition"
    for store in cluster.nodes[3].command_stores.all_stores():
        e = store.durable_before.entry(k(0).to_routing())
        assert e is None or e.majority_before is None, \
            "partitioned replica adopted a durability watermark"

    # heal: a fresh durability round's sync point witnesses the old writes as
    # deps; node 3 blocks on them, and the progress machinery fetches what it
    # missed — the round only completes once node 3 has actually applied
    link.active = False
    res2 = None
    for _attempt in range(8):  # the scheduling layer retries each cycle
        res2 = coordinate_shard_durable(cluster.nodes[1],
                                        Ranges.of(Range(k(0), k(1000))))
        assert cluster.run_until(res2.is_done, max_tasks=2_000_000)
        if res2.failure is None:
            break
        cluster.run_for(2.0)  # let progress-log fetch/apply catch node 3 up
    assert res2.failure is None, f"post-heal durability round failed: {res2.failure}"
    cluster.run_until_idle()
    # every replica holds identical, complete data
    lists = {tuple(sorted((key.value, cluster.stores[n].get(key))
                          for key in map(k, range(4))))
             for n in cluster.nodes}
    assert len(lists) == 1, lists
    for key in map(k, range(4)):
        assert cluster.stores[1].get(key) == (f"v{key.value}",)
