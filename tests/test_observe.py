"""The observability layer: metrics registry, txn lifecycle spans, flight
recorder, Chrome-trace export — and its two hard contracts:

1. ZERO OBSERVER EFFECT: a same-seed hostile burn with the flight recorder
   on vs off yields byte-identical full message traces and identical
   final-state outcome counters (the recorder's hooks may never allocate ids
   from shared RNG, read wall-clock, or alter scheduling).
2. REGISTRY COMPLETENESS: every wire MessageType and every Status/SaveStatus
   member has an explicit metric name (two-way agreement with the enums), so
   a new message or phase cannot ship unobserved.
"""
import json

import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.local.status import SaveStatus, Status
from cassandra_accord_tpu.messages.base import MessageType
from cassandra_accord_tpu.observe import (FlightRecorder, MetricsRegistry,
                                          validate_chrome_trace)
from cassandra_accord_tpu.observe import schema
from cassandra_accord_tpu.observe.registry import Histogram

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("a").inc()
    reg.counter("a").inc(4)
    reg.gauge("g", node=1).set(7)
    h = reg.histogram("h", node=1, store=0, bounds=(10, 100))
    h.record(5)
    h.record(50)
    h.record(5000)
    snap = reg.snapshot()
    assert snap["cluster"]["a"] == 5
    assert snap["node/1"]["g"] == 7
    hs = snap["store/1/0"]["h"]
    assert hs["count"] == 3 and hs["total"] == 5055
    assert hs["buckets"] == [1, 1, 1]   # <=10, <=100, overflow


def test_registry_rejects_kind_mismatch():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_registry_rejects_histogram_bounds_mismatch():
    reg = MetricsRegistry()
    reg.histogram("h", bounds=(10, 100))
    with pytest.raises(ValueError, match="bounds"):
        reg.histogram("h")   # default bounds differ: loud, not first-wins
    reg.histogram("h", bounds=(10, 100)).record(5)   # same bounds: fine


def test_snapshot_delta_merge():
    a = MetricsRegistry()
    b = MetricsRegistry()
    a.counter("n").inc(10)
    b.counter("n").inc(3)
    b.counter("only_b").inc(2)
    for reg, vals in ((a, (5, 500)), (b, (5,))):
        h = reg.histogram("h", bounds=(10, 100))
        for v in vals:
            h.record(v)
    sa, sb = a.snapshot(), b.snapshot()
    d = MetricsRegistry.delta(sa, sb)
    assert d["cluster"]["n"] == 7
    assert d["cluster"]["only_b"] == -2
    assert d["cluster"]["h"]["count"] == 1
    assert d["cluster"]["h"]["buckets"] == [0, 0, 1]
    m = MetricsRegistry.merge(sa, sb)
    assert m["cluster"]["n"] == 13
    assert m["cluster"]["h"]["count"] == 3


def test_snapshot_json_stable():
    """Same content in any insertion order renders the same JSON."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("x").inc(1)
    a.counter("y", node=2).inc(2)
    b.counter("y", node=2).inc(2)
    b.counter("x").inc(1)
    assert a.to_json() == b.to_json()
    json.loads(a.to_json())   # well-formed


def test_histogram_default_bounds_are_sim_latency_shaped():
    h = Histogram()
    h.record(1)            # 1us
    h.record(2_000_000)    # 2s
    assert h.count == 2 and h.counts[0] == 1


# ---------------------------------------------------------------------------
# registry completeness lint (the CI satellite): new messages/phases cannot
# ship unobserved
# ---------------------------------------------------------------------------

def test_every_message_type_has_a_metric_name():
    enum_names = {t.name for t in MessageType}
    missing = sorted(enum_names - set(schema.MESSAGE_METRICS))
    assert not missing, \
        f"MessageTypes with no metric name (add to observe/schema.py): {missing}"
    stale = sorted(set(schema.MESSAGE_METRICS) - enum_names)
    assert not stale, \
        f"metric names for nonexistent MessageTypes (remove from schema): {stale}"


def test_every_status_phase_has_a_metric_name():
    for enum_cls, mapping, label in (
            (Status, schema.STATUS_METRICS, "STATUS_METRICS"),
            (SaveStatus, schema.SAVE_STATUS_METRICS, "SAVE_STATUS_METRICS")):
        enum_names = {s.name for s in enum_cls}
        missing = sorted(enum_names - set(mapping))
        assert not missing, \
            f"{enum_cls.__name__} members with no metric name " \
            f"(add to observe/schema.py {label}): {missing}"
        stale = sorted(set(mapping) - enum_names)
        assert not stale, f"stale {label} entries: {stale}"
    # outcome classes are closed over the burn's resolve kinds
    assert set(schema.OUTCOME_METRICS) == set(schema.OUTCOMES)


def test_metric_name_lookups_raise_actionably():
    with pytest.raises(KeyError, match="observe/schema.py"):
        schema.metric_for_message("BOGUS_REQ")
    with pytest.raises(KeyError, match="observe/schema.py"):
        schema.metric_for_save_status("BOGUS")


def test_every_gauge_and_histogram_declares_unit_two_way():
    """Unit/time-plane lint: every gauge/histogram metric the schema knows
    declares its unit (sim_s | wall_s | bytes | count), and there are no
    stale unit entries for removed metrics — two-way, like the MessageType
    completeness check."""
    known = ({schema.LATENCY_METRIC, schema.SERVICE_BATCH_SIZE_METRIC}
             | set(schema.RESOLVER_METRICS.values())
             | set(schema.SERVICE_STAT_METRICS.values())
             | set(schema.STORE_GAUGE_METRICS.values()))
    missing = sorted(known - set(schema.METRIC_UNITS))
    assert not missing, \
        f"gauge/histogram metrics with no unit declaration (add to " \
        f"observe/schema.py METRIC_UNITS): {missing}"
    stale = sorted(set(schema.METRIC_UNITS) - known)
    assert not stale, f"stale METRIC_UNITS entries: {stale}"
    bad = {k: v for k, v in schema.METRIC_UNITS.items()
           if v not in schema.UNITS}
    bad.update({k: v for k, v in schema.METRIC_UNIT_PREFIXES.items()
                if v not in schema.UNITS})
    assert not bad, f"units outside the {schema.UNITS} vocabulary: {bad}"
    # wall-clock values are forbidden in the registry entirely: snapshots
    # are diffed across same-seed runs (the wall plane lives in
    # observe/profiler.py reports)
    walls = [k for k, v in schema.METRIC_UNITS.items() if v == "wall_s"]
    assert not walls, f"wall-clock metrics registered in the deterministic " \
                      f"registry: {walls}"


def test_every_schema_metric_declares_timeline_policy_two_way():
    """Timeline-policy lint: every metric the schema registers resolves to a
    declared timeline policy (rate | sample | percentile | excluded), there
    are no stale explicit entries for removed metrics, and the policy
    vocabulary is closed — two-way, like METRIC_UNITS."""
    known = ({schema.SUBMITTED_METRIC, schema.LATENCY_METRIC,
              schema.SERVICE_BATCH_SIZE_METRIC,
              schema.TIMELINE_IN_FLIGHT_METRIC}
             | set(schema.MESSAGE_METRICS.values())
             | set(schema.STATUS_METRICS.values())
             | set(schema.SAVE_STATUS_METRICS.values())
             | set(schema.OUTCOME_METRICS.values())
             | set(schema.RESOLVER_METRICS.values())
             | set(schema.SERVICE_STAT_METRICS.values())
             | set(schema.STORE_GAUGE_METRICS.values()))
    for name in sorted(known):
        schema.timeline_policy_for(name)   # KeyError = undeclared, tier-1
    # no stale EXPLICIT entries (prefix families are covered by resolution)
    stale = sorted(set(schema.TIMELINE_POLICIES) - known)
    assert not stale, f"stale TIMELINE_POLICIES entries: {stale}"
    bad = {k: v for k, v in schema.TIMELINE_POLICIES.items()
           if v not in schema.TIMELINE_POLICY_VALUES}
    bad.update({k: v for k, v in schema.TIMELINE_POLICY_PREFIXES.items()
                if v not in schema.TIMELINE_POLICY_VALUES})
    assert not bad, \
        f"policies outside the {schema.TIMELINE_POLICY_VALUES} vocabulary: {bad}"
    # undeclared metrics raise actionably (the live half of the lint —
    # observe/timeline.Timeline enforces this on every feed)
    with pytest.raises(KeyError, match="TIMELINE_POLICIES"):
        schema.timeline_policy_for("bogus.metric")
    # spot anchors: the headline series carry the intended policies
    assert schema.timeline_policy_for(schema.LATENCY_METRIC) == "percentile"
    assert schema.timeline_policy_for(schema.SUBMITTED_METRIC) == "rate"
    assert schema.timeline_policy_for(schema.TIMELINE_IN_FLIGHT_METRIC) \
        == "sample"


def test_observed_burn_gauges_all_resolve_units():
    """Every gauge/histogram a real instrumented burn actually registers
    resolves through unit_for — dynamic sim.* mirrors included; an
    undeclared metric raises actionably."""
    from cassandra_accord_tpu.observe.registry import Gauge
    rec = FlightRecorder()
    run_burn(14, ops=20, concurrency=4, resolver="verify", observer=rec)
    rec.metrics_snapshot()   # pull-collects the cluster gauges
    seen = set()
    for (_scope, name), metric in rec.registry._metrics.items():
        if isinstance(metric, (Gauge, Histogram)):
            seen.add(name)
            schema.unit_for(name)   # raises KeyError on an undeclared one
    assert schema.LATENCY_METRIC in seen
    assert any(n.startswith("store.") for n in seen)
    assert any(n.startswith("sim.") for n in seen)
    assert schema.unit_for(schema.LATENCY_METRIC) == "sim_s"
    with pytest.raises(KeyError, match="METRIC_UNITS"):
        schema.unit_for("bogus.metric")


# ---------------------------------------------------------------------------
# trace ring buffer (satellite: bounded memory for long burns)
# ---------------------------------------------------------------------------

def test_trace_ring_buffer_keeps_last_n():
    t = Trace(keep_last=100)
    for i in range(250):
        t.hook("DELIVER", 1, 2, i, object(), i * 10)
    assert len(t) == 100
    assert t.dropped == 150
    events = list(t.events)
    assert events[0][0] == 150 and events[-1][0] == 249   # absolute seqs
    # unbounded mode unchanged
    u = Trace()
    for i in range(250):
        u.hook("DELIVER", 1, 2, i, object(), i * 10)
    assert len(u) == 250 and u.dropped == 0
    # keep_last=0 means "count, keep nothing" — not unbounded
    z = Trace(keep_last=0)
    for i in range(7):
        z.hook("DELIVER", 1, 2, i, object(), i)
    assert len(z) == 0 and z.dropped == 7
    with pytest.raises(ValueError):
        Trace(keep_last=-1)


def test_ring_traces_still_diff():
    a, b = Trace(keep_last=50), Trace(keep_last=50)
    for i in range(120):
        a.hook("DELIVER", 1, 2, i, object(), i)
        b.hook("DELIVER", 1, 2, i, object(), i)
    assert diff_traces(a, b) is None
    b.hook("DROP", 1, 2, 999, object(), 999)
    assert diff_traces(a, b) is not None


# ---------------------------------------------------------------------------
# span accounting: the outcome partition
# ---------------------------------------------------------------------------

def _outcome_partition(snapshot_cluster):
    return {o: snapshot_cluster.get(schema.OUTCOME_METRICS[o], 0)
            for o in schema.OUTCOMES}


def test_benign_burn_span_accounting():
    rec = FlightRecorder()
    result = run_burn(11, ops=30, concurrency=6, observer=rec)
    c = rec.metrics_snapshot()["cluster"]
    assert c[schema.SUBMITTED_METRIC] == result.ops_submitted == 30
    partition = _outcome_partition(c)
    assert sum(partition.values()) == 30
    # benign network: everything acked, split fast/slow only
    assert partition["fast"] + partition["slow"] == result.ops_ok == 30
    assert partition["recovered"] == partition["invalidated"] == 0
    assert c[schema.LATENCY_METRIC]["count"] == 30
    # every client span is classified, resolved, and carries per-node
    # per-store lifecycle transitions with sim timestamps
    spans = rec.spans.client_spans()
    assert len(spans) == 30
    for span in spans:
        assert span.path in ("fast", "slow")
        assert span.outcome in schema.OUTCOMES
        assert span.resolved_us is not None \
            and span.resolved_us >= span.submitted_us
        assert span.transitions, f"span {span.txn_id} has no transitions"
        for (node, store), transitions in span.transitions.items():
            statuses = [s for s, _ts in transitions]
            assert all(s in schema.SAVE_STATUS_METRICS for s in statuses)
            times = [ts for _s, ts in transitions]
            assert times == sorted(times), "transitions out of sim order"
    # per-node and per-store scopes are populated
    snap = rec.metrics_snapshot()
    assert any(s.startswith("node/") for s in snap)
    assert any(s.startswith("store/") for s in snap)


def test_span_dict_schema():
    rec = FlightRecorder()
    run_burn(12, ops=10, concurrency=4, observer=rec)
    d = rec.spans.to_list()[0]
    assert set(d) == {"txn_id", "op_id", "coordinator", "submitted_us",
                      "resolved_us", "path", "outcome", "recoveries",
                      "invalidate_attempts", "timeouts", "backoffs",
                      "transitions"}


# ---------------------------------------------------------------------------
# the tentpole invariant: zero observer effect
# ---------------------------------------------------------------------------

def test_zero_observer_effect_hostile():
    """Same-seed hostile burn with the flight recorder ON vs OFF: identical
    full message traces (diff_traces is None) and identical outcomes — the
    in-tree proof that metrics collection never perturbs the simulation."""
    ta, tb = Trace(), Trace()
    bare = run_burn(9, tracer=ta.hook, **HOSTILE)
    rec = FlightRecorder()
    observed = run_burn(9, tracer=tb.hook, observer=rec, **HOSTILE)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"flight recorder perturbed the simulation:\n{divergence}"
    assert (bare.ops_ok, bare.ops_recovered, bare.ops_nacked, bare.ops_lost,
            bare.ops_failed, bare.sim_micros) == \
           (observed.ops_ok, observed.ops_recovered, observed.ops_nacked,
            observed.ops_lost, observed.ops_failed, observed.sim_micros)
    # message stats identical too (tier-choice counters are wall-clock
    # driven and excluded from the determinism contract, as in reconcile)
    tier_keys = ("resolver_host_consults", "resolver_native_consults",
                 "resolver_device_consults", "resolver_service_submitted",
                 "resolver_service_batches")
    sa = {k: v for k, v in bare.stats.items() if k not in tier_keys}
    sb = {k: v for k, v in observed.stats.items() if k not in tier_keys}
    assert sa == sb
    # and the recording itself is coherent: the outcome partition covers
    # every submitted op exactly once
    c = rec.metrics_snapshot()["cluster"]
    assert sum(_outcome_partition(c).values()) == c[schema.SUBMITTED_METRIC] \
        == observed.ops_submitted


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------

def test_chrome_trace_valid_and_counts_agree():
    """A hostile burn's --trace-out artifact is schema-valid Chrome trace
    JSON whose client span count equals the registry's submitted total and
    whose outcome partition (fast+slow+recovered+invalidated+lost+failed)
    sums to it."""
    rec = FlightRecorder()
    result = run_burn(13, **HOSTILE, observer=rec)
    doc = rec.chrome_trace()
    problems = validate_chrome_trace(doc)
    assert problems == [], f"invalid Chrome trace: {problems[:5]}"
    # JSON-serializable end to end
    json.loads(json.dumps(doc))
    c = rec.metrics_snapshot()["cluster"]
    client_events = [e for e in doc["traceEvents"]
                     if e.get("cat") == "txn" and e["ph"] == "X"]
    assert len(client_events) == c[schema.SUBMITTED_METRIC] \
        == result.ops_submitted
    assert sum(_outcome_partition(c).values()) == result.ops_submitted
    # lifecycle tracks exist (pid per node, tid per store), message instants
    cats = {e.get("cat") for e in doc["traceEvents"]}
    assert "lifecycle" in cats and "msg" in cats
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in doc["traceEvents"])


def test_chrome_trace_counter_tracks():
    """Counter-track satellite: ``C`` events sampled on sim-time buckets for
    in-flight txns and recovery attempts, derived at EXPORT time (no runtime
    sampling), on the synthetic counters pid; schema-checked."""
    from cassandra_accord_tpu.observe.export import COUNTER_PID, counter_events
    rec = FlightRecorder()
    run_burn(13, **HOSTILE, observer=rec)
    doc = rec.chrome_trace()
    assert validate_chrome_trace(doc) == []
    cs = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert cs, "no counter events exported"
    assert {e["name"] for e in cs} == {"in_flight_txns", "recovery_attempts"}
    assert all(e["pid"] == COUNTER_PID for e in cs)
    inflight = [e for e in cs if e["name"] == "in_flight_txns"]
    # sampled series: monotone time, in-flight returns to 0 once all resolve
    times = [e["ts"] for e in inflight]
    assert times == sorted(times)
    assert inflight[-1]["args"]["in_flight"] == 0
    assert max(e["args"]["in_flight"] for e in inflight) > 0
    rec2 = [e for e in cs if e["name"] == "recovery_attempts"]
    assert rec2[-1]["args"]["recoveries"] >= rec2[0]["args"]["recoveries"]
    # the counters process is named in metadata
    assert any(e["ph"] == "M" and e["pid"] == COUNTER_PID
               and e["args"]["name"] == "cluster counters"
               for e in doc["traceEvents"])
    # an empty recorder exports no counter track (and stays schema-valid)
    empty = FlightRecorder()
    assert counter_events(empty) == []
    assert validate_chrome_trace(empty.chrome_trace()) == []


def test_validate_chrome_trace_rejects_bad_counter_events():
    base = {"name": "x", "cat": "counter", "ph": "C", "ts": 1, "pid": 0,
            "tid": 0}
    bad_missing = dict(base)                      # no args at all
    bad_type = dict(base, args={"v": "high"})     # non-numeric series
    ok = dict(base, args={"v": 3})
    assert validate_chrome_trace({"traceEvents": [ok]}) == []
    for bad in (bad_missing, bad_type):
        problems = validate_chrome_trace({"traceEvents": [bad]})
        assert problems, f"accepted invalid C event {bad}"


def test_message_ring_bounds_flight_recorder():
    rec = FlightRecorder(message_ring=500)
    run_burn(11, ops=30, concurrency=6, observer=rec)
    assert len(rec.messages) == 500
    assert rec.dropped_messages > 0
    assert validate_chrome_trace(rec.chrome_trace()) == []


# ---------------------------------------------------------------------------
# burn CLI: --metrics-out / --trace-out / --json enrichment / --progress
# ---------------------------------------------------------------------------

def test_burn_cli_artifacts(tmp_path, capsys):
    from cassandra_accord_tpu.harness import burn as burn_cli
    m, t, j = tmp_path / "m.json", tmp_path / "t.json", tmp_path / "j.json"
    burn_cli.main(["--seeds", "1", "--ops", "20", "--no-cache-miss",
                   "--metrics-out", str(m), "--trace-out", str(t),
                   "--json", str(j), "--progress", "0.5"])
    metrics = json.loads(m.read_text())
    assert metrics["cluster"][schema.SUBMITTED_METRIC] == 20
    trace = json.loads(t.read_text())
    assert validate_chrome_trace(trace) == []
    summary = json.loads(j.read_text())
    entry = summary["results"][0]
    assert entry["status"] == "pass"
    # --json enrichment: the cluster-scope registry rides along per seed
    assert entry["metrics"][schema.SUBMITTED_METRIC] == 20
    assert sum(_outcome_partition(entry["metrics"]).values()) == 20
    # the heartbeat printed at least one progress line
    out = capsys.readouterr().out
    assert "resolved=" in out and "in_flight=" in out


def test_progress_heartbeat_lines(capsys):
    # interval well inside the active phase: a tiny benign burn resolves all
    # ops within a few hundred sim-ms (the later sim-time is timeout drain)
    run_burn(11, ops=20, concurrency=4, progress_every_s=0.05,
             progress_label="hb-test")
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.startswith("[burn hb-test]")]
    assert lines, "no heartbeat lines printed"
    assert "resolved=" in lines[0] and "in_flight=" in lines[0]


# ---------------------------------------------------------------------------
# device-resolver counter unification
# ---------------------------------------------------------------------------

def test_resolver_counters_unified_into_registry():
    """The same counters the burn result reports (resolver_*) land in the
    registry under resolver.* — one source for burns and bench.py."""
    rec = FlightRecorder()
    result = run_burn(14, ops=20, concurrency=4, resolver="verify",
                      observer=rec)
    snap = rec.metrics_snapshot()
    c = snap["cluster"]
    for name in schema.RESOLVER_COUNTERS:
        assert schema.RESOLVER_METRICS[name] in c, \
            f"resolver counter {name} not collected"
        assert c[schema.RESOLVER_METRICS[name]] == \
            result.stats.get(f"resolver_{name}", 0)
    # per-store scope too
    store_scopes = [s for s in snap if s.startswith("store/")]
    assert any(schema.RESOLVER_METRICS["walk_consults"] in snap[s]
               for s in store_scopes)


def test_histogram_percentile_estimate():
    h = Histogram(bounds=(10, 100, 1000))
    for v in (5, 5, 50, 500):
        h.record(v)
    assert h.percentile(0.50) == 10     # 2/4 inside the <=10 bucket
    assert h.percentile(0.75) == 100
    assert h.percentile(1.0) == 1000
    assert Histogram(bounds=(10,)).percentile(0.5) is None
    h.record(50_000)                    # overflow bucket: unbounded above
    assert h.percentile(1.0) is None
    # the snapshot-dict form is the same formula (bench.py protocol_slo)
    assert Histogram.snapshot_percentile(h.to_snapshot(), 0.5) == 100


def test_launch_mfu_formula():
    from cassandra_accord_tpu.observe.device import (PEAK_BF16_TFLOPS,
                                                     launch_mfu)
    out = launch_mfu(t=1000, k=512, rows=256, seconds=0.001)
    # 2*256*512*1000 FLOPs / 1ms = 0.262 TFLOP/s
    assert out["launch_join_tflops"] == pytest.approx(0.2621, abs=1e-3)
    assert out["launch_mfu_vs_275tflops"] == pytest.approx(
        out["launch_join_tflops"] / PEAK_BF16_TFLOPS, abs=1e-6)


def test_kernel_consult_metrics_formulas():
    from cassandra_accord_tpu.observe.device import (
        PEAK_BF16_TFLOPS, consult_join_flops, index_bytes_int8,
        kernel_consult_metrics)
    assert consult_join_flops(b=2, k=3, t=5) == 60.0
    assert index_bytes_int8(t=10, k=4) == 80
    out = kernel_consult_metrics(t=1000, k=512, b=256, device_qps=256_000.0)
    # 1000 launches/s x 2*256*512*1000 FLOPs = 0.262 TFLOP/s
    assert out["device_join_tflops"] == pytest.approx(0.2621, abs=1e-3)
    assert out["consult_mfu_vs_275tflops"] == pytest.approx(
        out["device_join_tflops"] / PEAK_BF16_TFLOPS, abs=1e-5)
    assert out["index_bytes_int8"] == 2 * 1000 * 512
