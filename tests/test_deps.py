"""KeyDeps/RangeDeps/Deps CSR multimap semantics.

Parity targets: KeyDepsTest/RangeDepsTest/DepsTest
(accord-core/src/test/java/accord/primitives/KeyDepsTest.java:1-619) — build, merge,
slice, invert, without — checked against dict/set oracles.
"""
from collections import defaultdict

from cassandra_accord_tpu.primitives.deps import (
    Deps, DepsBuilder, KeyDeps, KeyDepsBuilder, RangeDeps, RangeDepsBuilder,
)
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def r(a, b):
    return Range(k(a), k(b))


def tid(hlc, node=1, kind=TxnKind.WRITE, domain=Domain.KEY):
    return TxnId(1, hlc, node, kind, domain)


def build_random(rng, nkeys=10, ntxn=20):
    oracle = defaultdict(set)
    b = KeyDepsBuilder()
    for _ in range(rng.next_int(1, 60)):
        key = k(rng.next_int(nkeys))
        t = tid(rng.next_int(ntxn), rng.next_int(1, 4))
        b.add(key, t)
        oracle[key].add(t)
    return b.build(), oracle


def as_dict(kd: KeyDeps):
    out = {}
    kd.for_each_key(lambda key, tids: out.__setitem__(key, set(tids)))
    return out


def test_keydeps_build_and_access():
    a, b, c = tid(1), tid(2), tid(3)
    kd = KeyDeps.of({k(1): [a, b], k(2): [b, c]})
    assert kd.txn_id_count() == 3
    assert kd.txn_ids_for(k(1)) == [a, b]
    assert kd.txn_ids_for(k(2)) == [b, c]
    assert kd.txn_ids_for(k(9)) == []
    assert kd.contains(b) and not kd.contains(tid(99))
    assert kd.max_txn_id() == c


def test_keydeps_invert_participants():
    a, b = tid(1), tid(2)
    kd = KeyDeps.of({k(1): [a], k(2): [a, b], k(3): [b]})
    assert [x.value for x in kd.participants(a)] == [1, 2]
    assert [x.value for x in kd.participants(b)] == [2, 3]
    assert list(kd.participants(tid(77))) == []


def test_keydeps_merge_equals_oracle_union():
    rng = RandomSource(42)
    for _ in range(30):
        kd1, o1 = build_random(rng)
        kd2, o2 = build_random(rng)
        merged = KeyDeps.merge([kd1, kd2])
        oracle = defaultdict(set)
        for o in (o1, o2):
            for key, s in o.items():
                oracle[key] |= s
        assert as_dict(merged) == {key: s for key, s in oracle.items() if s}


def test_keydeps_slice_without():
    rng = RandomSource(43)
    for _ in range(30):
        kd, oracle = build_random(rng)
        lo, hi = rng.next_int(0, 5), rng.next_int(5, 11)
        sliced = kd.slice(Ranges.of(r(lo, hi)))
        expect = {key: s for key, s in oracle.items() if lo <= key.value < hi}
        assert as_dict(sliced) == expect
        # txn ids not referenced by any kept key must be dropped
        refd = set().union(*expect.values()) if expect else set()
        assert set(sliced.txn_ids) == refd

        cutoff = tid(10)
        filtered = kd.without(lambda t: t < cutoff)
        expect2 = {key: {t for t in s if not t < cutoff} for key, s in oracle.items()}
        expect2 = {key: s for key, s in expect2.items() if s}
        assert as_dict(filtered) == expect2


def test_rangedeps_stabbing_and_slice():
    a, b, c = tid(1), tid(2), tid(3, domain=Domain.RANGE)
    rd = RangeDeps.of({r(0, 10): [a], r(5, 15): [b, c], r(20, 30): [c]})
    assert rd.intersecting_txn_ids(k(7)) == sorted([a, b, c])
    assert rd.intersecting_txn_ids(k(12)) == sorted([b, c])
    assert rd.intersecting_txn_ids(k(25)) == [c]
    assert rd.intersecting_txn_ids(k(16)) == []
    assert rd.intersecting_txn_ids(r(8, 21)) == sorted([a, b, c])
    sliced = rd.slice(Ranges.of(r(0, 6)))
    assert sliced.intersecting_txn_ids(k(5)) == sorted([a, b, c])
    assert sliced.intersecting_txn_ids(k(7)) == []


def test_rangedeps_participants_without_merge():
    a, b = tid(1), tid(2)
    rd = RangeDeps.of({r(0, 10): [a], r(20, 30): [a, b]})
    assert list(rd.participants(a)) == [r(0, 10), r(20, 30)]
    assert list(rd.participants(b)) == [r(20, 30)]
    rd2 = rd.without(lambda t: t == a)
    assert rd2.intersecting_txn_ids(r(0, 100)) == [b]
    m = RangeDeps.merge([rd, RangeDeps.of({r(40, 50): [b]})])
    assert m.intersecting_txn_ids(r(0, 100)) == [a, b]


def test_deps_builder_routing():
    """DepsBuilder routes adds by domain + managesExecution (Deps.java:80-106)."""
    w = tid(1)                                    # key write -> key_deps
    sp = tid(2, kind=TxnKind.SYNC_POINT)          # key sync point -> direct_key_deps
    rw = tid(3, domain=Domain.RANGE)              # range txn -> range_deps
    b = DepsBuilder()
    b.add(k(1), w)
    b.add(k(1), sp)
    b.add(r(0, 5), rw)
    d = b.build()
    assert d.key_deps.contains(w) and not d.key_deps.contains(sp)
    assert d.direct_key_deps.contains(sp)
    assert d.range_deps.contains(rw)
    assert set(d.txn_ids()) == {w, sp, rw}
    assert d.contains(w) and d.contains(sp) and d.contains(rw)


def test_deps_merge_slice():
    d1 = DepsBuilder().add(k(1), tid(1)).build()
    d2 = DepsBuilder().add(k(2), tid(2)).build()
    m = Deps.merge([d1, d2])
    assert m.txn_id_count() == 2
    s = m.slice(Ranges.of(r(0, 2)))
    assert s.txn_ids() == [tid(1)]
