"""LocalConfig: one injected config object (config/LocalConfig.java parity)."""
import subprocess
from pathlib import Path

from cassandra_accord_tpu.config import LocalConfig

REPO = str(Path(__file__).resolve().parents[1])


def test_from_env_reads_and_overrides(monkeypatch):
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "7")
    monkeypatch.setenv("ACCORD_RESOLVER", "verify")
    cfg = LocalConfig.from_env()
    assert cfg.tpu_walk_max == 7
    assert cfg.resolver_kind == "verify"
    over = LocalConfig.from_env(tpu_walk_max=99, max_read_rounds=5)
    assert over.tpu_walk_max == 99 and over.max_read_rounds == 5


def test_injected_config_overrides_env(monkeypatch):
    """The object is the override surface: a Node built with an explicit
    config ignores the environment (MutableLocalConfig role)."""
    monkeypatch.setenv("ACCORD_RESOLVER", "cpu")
    from cassandra_accord_tpu.harness.cluster import Cluster
    from cassandra_accord_tpu.primitives.keys import IntKey, Range
    from cassandra_accord_tpu.topology.topology import Shard, Topology
    cfg = LocalConfig(resolver_kind="verify", tpu_walk_max=3,
                      max_read_rounds=4)
    shards = [Shard(Range(IntKey(0), IntKey(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=5, node_config=cfg)
    for node in cluster.nodes.values():
        assert node.config is cfg
        assert node.resolver_kind == "verify"
        for cs in node.command_stores.all_stores():
            assert cs.resolver.tpu.config is cfg
            assert cs.resolver.tpu._walk_max == 3


def test_no_scattered_env_reads_in_protocol_code():
    """VERDICT r04 item 10 done-criterion: protocol code reads knobs through
    LocalConfig, not os.environ (harness/maelstrom/utils excluded: test
    tooling and the paranoia tier keep their env hooks)."""
    out = subprocess.run(
        ["grep", "-rln", "os.environ",
         "--include=*.py",
         "cassandra_accord_tpu/local", "cassandra_accord_tpu/coordinate",
         "cassandra_accord_tpu/messages", "cassandra_accord_tpu/impl",
         "cassandra_accord_tpu/topology", "cassandra_accord_tpu/primitives"],
        capture_output=True, text=True, cwd=REPO)
    assert out.stdout.strip() == "", \
        f"protocol files still read os.environ: {out.stdout}"
