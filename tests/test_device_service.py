"""Persistent batched device consult service (cassandra_accord_tpu/device_service/).

Covers the ISSUE-6 contracts:

- ragged batch ingress (flat keys + row offsets): empty rows, duplicate
  keys, max-width rows — batched answers equal per-txn host answers;
- jit-shape discipline: a steady-state stream triggers a BOUNDED number of
  kernel compilations (pow2 bucket shapes; second half of the stream
  compiles nothing new);
- double-buffered snapshot semantics: an open window answers against the
  index as of the window's opening, while one-shot consults see the
  current index;
- counter bookkeeping: ``device_consults`` increments exactly once per
  SUBMITTED consult, not per batch/launch;
- zero observer effect: enabling the service under the hostile burn leaves
  same-seed runs byte-identical (deterministic fallback and kernel backend
  both).
"""
import numpy as np
import pytest

from cassandra_accord_tpu.device_service.batch import (build_batch,
                                                       pow2_bucket,
                                                       split_rows)
from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.impl.resolver import CpuDepsResolver
from cassandra_accord_tpu.impl.tpu_resolver import TpuDepsResolver
from cassandra_accord_tpu.local.cfk import InternalStatus
from cassandra_accord_tpu.primitives.keys import IntKey
from cassandra_accord_tpu.primitives.timestamp import (Domain, Timestamp,
                                                       TxnId, TxnKind)
from cassandra_accord_tpu.utils.random import RandomSource

from tests.test_resolver import _FakeStore, k, rk, tid


def make_service_resolver(txn_capacity=64, key_capacity=64, backend="jax"):
    """A TpuDepsResolver forced onto the service device tier (jax runs on
    the CPU backend under tier-1; that IS the kernel tier) + the cfk-walk
    oracle on the same store."""
    from cassandra_accord_tpu.config import LocalConfig
    store = _FakeStore()
    cfg = LocalConfig.from_env(tpu_service="on", tpu_service_backend=backend,
                               tpu_tier="device")
    r = TpuDepsResolver(store, txn_capacity=txn_capacity,
                        key_capacity=key_capacity, config=cfg)
    r.tier = "device"
    return store, r, CpuDepsResolver(store)


def register_both(store, resolver, txn_id, status, execute_at, keys):
    indexed = tuple(key for key in keys
                    if store.cfk(key).update(txn_id, status, execute_at))
    if indexed:
        resolver.register(txn_id, status, execute_at, indexed)


# ---------------------------------------------------------------------------
# batch ingress contract
# ---------------------------------------------------------------------------

def test_pow2_buckets():
    assert pow2_bucket(1, 8) == 8
    assert pow2_bucket(8, 8) == 8
    assert pow2_bucket(9, 8) == 16
    assert pow2_bucket(300, 8, 256) == 256
    assert split_rows(list(range(10)), 4) == [[0, 1, 2, 3], [4, 5, 6, 7],
                                              [8, 9]]
    assert split_rows([], 4) == []


def test_ragged_batch_shapes_and_densify():
    rows = [(0, 1), (), (2, 2, 2), tuple(range(7))]   # empty + dups + wide
    b = build_batch(rows, [(1, 0, 0, 0, 0)] * 4, [0] * 4)
    assert b.rows == 4
    assert b.before.shape[0] == 8                     # row bucket floor
    assert b.flat_cols.shape[0] == 16                 # flat bucket floor
    assert b.offsets[1] - b.offsets[0] == 2
    assert b.offsets[2] - b.offsets[1] == 0           # empty row
    assert list(b.offsets[4:]) == [12] * 5            # padding rows width 0
    q = b.densify(8)
    assert q[0].tolist() == [1, 1, 0, 0, 0, 0, 0, 0]
    assert q[1].sum() == 0
    assert q[2].tolist() == [0, 0, 1, 0, 0, 0, 0, 0]  # dups collapse
    assert q[3].sum() == 7


def test_batch_over_cap_raises():
    with pytest.raises(ValueError):
        build_batch([(0,)] * 9, [(0,) * 5] * 9, [0] * 9, row_cap=8)


# ---------------------------------------------------------------------------
# ragged-batch correctness: batched service consults == per-txn host consults
# ---------------------------------------------------------------------------

def _random_index(store, resolver, rng, keys, n_txns=120):
    hlc = 0
    live = []
    for _ in range(n_txns):
        hlc += rng.next_int(1, 4)
        kind = rng.pick([TxnKind.WRITE, TxnKind.READ, TxnKind.WRITE])
        t = tid(hlc, node=1 + rng.next_int(3), kind=kind)
        ks = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 5))})
        register_both(store, resolver, t, InternalStatus.PREACCEPTED, None, ks)
        live.append((t, ks))
        if live and rng.next_float() < 0.4:
            t2, ks2 = rng.pick(live)
            st = rng.pick([InternalStatus.ACCEPTED, InternalStatus.COMMITTED,
                           InternalStatus.STABLE, InternalStatus.APPLIED])
            ea = Timestamp(1, hlc + rng.next_int(10), 0, t2.node) \
                if st in (InternalStatus.ACCEPTED, InternalStatus.COMMITTED,
                          InternalStatus.STABLE) else None
            register_both(store, resolver, t2, st, ea, ks2)
    return hlc


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_ragged_property_batched_equals_per_txn(seed):
    """Randomized ragged windows (empty key sets, duplicate keys, max-width
    rows) through the production prefetch→futures path must equal the
    per-txn cfk walk, query for query (the resolver's elision gate routes
    below-covering-bound rows to the exact path, exactly as live traffic)."""
    from cassandra_accord_tpu.impl.resolver import QuerySpec
    rng = RandomSource(seed)
    store, resolver, oracle = make_service_resolver()
    keys = [rk(i * 10) for i in range(10)]
    hlc = _random_index(store, resolver, rng, keys)
    svc = resolver.service()
    windows = 0
    for _round in range(8):
        hlc += 1
        specs = []
        queries = []
        for _q in range(rng.next_int(1, 9)):
            hlc += 1
            q = tid(hlc, kind=rng.pick([TxnKind.WRITE, TxnKind.READ]))
            width = rng.pick([0, 1, 2, len(keys)])    # empty + max-width rows
            qk = [rng.pick(keys) for _ in range(width)]
            if qk and rng.next_boolean():
                qk = qk + [qk[0]]                     # duplicate keys
            before = q.as_timestamp()
            specs.append(QuerySpec("kc", q, qk, before))
            if rng.next_boolean():
                specs.append(QuerySpec("mc", None, qk, None))
            queries.append((q, qk, before))
        resolver.prefetch(specs)
        windows += 1
        for q, qk, before in queries:
            got = resolver.key_conflicts(q, qk, before)
            # set-level comparison: batched attribution is per (key, txn)
            # incidence over the DEDUPED key set
            expect = oracle.key_conflicts(q, sorted(set(qk)), before)
            assert sorted(set(got)) == sorted(set(expect))
            assert resolver.max_conflict_keys(qk) \
                == oracle.max_conflict_keys(sorted(set(qk)))
        resolver.end_batch()
    assert resolver.device_consults > 0
    assert svc.submitted > 0, "prefetch must route through the service"


def test_oneshot_consult_matches_walk_oracle():
    """The immediate (non-window) service path: key_conflicts/max_conflict
    through consult_rows vs the cfk walk, including after prunes."""
    rng = RandomSource(77)
    store, resolver, oracle = make_service_resolver()
    keys = [rk(i * 10) for i in range(8)]
    hlc = _random_index(store, resolver, rng, keys, n_txns=80)
    for key in keys[:3]:
        cfk = store.cfks.get(key)
        if cfk is not None:
            resolver.on_pruned(key, cfk.prune_applied_before(tid(hlc + 1)))
    for _ in range(30):
        hlc += 2
        q = tid(hlc, kind=rng.pick([TxnKind.WRITE, TxnKind.READ]))
        qk = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 5))})
        assert sorted(resolver.key_conflicts(q, qk, q.as_timestamp())) \
            == sorted(oracle.key_conflicts(q, qk, q.as_timestamp()))
        assert resolver.max_conflict_keys(qk) == oracle.max_conflict_keys(qk)
    assert resolver.device_consults > 0


# ---------------------------------------------------------------------------
# double-buffered snapshot semantics
# ---------------------------------------------------------------------------

def test_window_answers_against_pinned_snapshot():
    """A window pins the index as of begin_window: a registration landing
    mid-window must not appear in the window's deferred answers, while a
    fresh one-shot consult (current index) must see it."""
    store, resolver, oracle = make_service_resolver()
    key = rk(10)
    register_both(store, resolver, tid(10), InternalStatus.PREACCEPTED,
                  None, [key])
    svc = resolver.service()
    svc.begin_window()
    q = tid(100)
    fut = svc.submit([resolver.key_slot[key]], q.as_timestamp().pack_lanes(),
                     int(q.kind), post=resolver._post_kc([key]))
    # mid-window registration (a NEW txn on the same key)
    register_both(store, resolver, tid(50), InternalStatus.PREACCEPTED,
                  None, [key])
    got = {t for _k, t in fut.result()}
    assert got == {tid(10)}, "snapshot window must not see mid-window txns"
    svc.end_window()
    # one-shot consult sees the current index
    now = {t for _k, t in resolver.key_conflicts(tid(101), [key],
                                                 tid(101).as_timestamp())}
    assert now == {tid(10), tid(50)}
    assert svc.index.incremental_refreshes + svc.index.full_uploads >= 2


def test_incremental_refresh_not_full_reupload():
    """Steady mutation + consult interleave must refresh by rows, not by
    whole-index re-upload (the r05 wedge shape)."""
    store, resolver, _ = make_service_resolver(txn_capacity=256,
                                               key_capacity=64)
    keys = [rk(i * 10) for i in range(8)]
    # warm: fill past the first view tier, one consult to establish buffers
    for i in range(80):
        register_both(store, resolver, tid(10 + i, node=1 + i % 3),
                      InternalStatus.PREACCEPTED, None,
                      [keys[i % len(keys)]])
    resolver.key_conflicts(tid(500), keys[:2], tid(500).as_timestamp())
    svc = resolver.service()
    full_before = svc.index.full_uploads
    for i in range(40):
        register_both(store, resolver, tid(1000 + i, node=1 + i % 3),
                      InternalStatus.PREACCEPTED, None,
                      [keys[i % len(keys)]])
        resolver.key_conflicts(tid(2000 + i), [keys[i % len(keys)]],
                               tid(2000 + i).as_timestamp())
    assert svc.index.incremental_refreshes >= 30
    assert svc.index.full_uploads == full_before, \
        "steady-state consults must not re-upload the whole index"


# ---------------------------------------------------------------------------
# jit-shape discipline (bounded compilations in steady state)
# ---------------------------------------------------------------------------

def test_steady_state_compilations_bounded():
    """Replaying a steady-state stream of varying window sizes compiles a
    BOUNDED kernel set: shapes appear while buckets/views warm up, then the
    second half of the stream adds ZERO new shapes."""
    rng = RandomSource(5)
    store, resolver, _ = make_service_resolver(txn_capacity=256,
                                               key_capacity=64)
    keys = [rk(i * 10) for i in range(8)]
    _random_index(store, resolver, rng, keys, n_txns=100)
    svc = resolver.service()

    hlc_box = [10_000]

    def drive(rounds):
        # deterministic cycle of window sizes and row widths: both halves of
        # the stream exercise the SAME shape mix, so steady state is exact
        sizes = [1, 3, 8, 12]
        widths = [0, 1, 2, 3]
        for r in range(rounds):
            svc.begin_window()
            futs = []
            for q_i in range(sizes[r % len(sizes)]):
                hlc_box[0] += 1
                q = tid(hlc_box[0])
                qk = [keys[(q_i + j) % len(keys)]
                      for j in range(widths[(r + q_i) % len(widths)])]
                known = [x for x in qk if x in resolver.key_slot]
                cols = [resolver.key_slot[x] for x in known]
                futs.append(svc.submit(cols, q.as_timestamp().pack_lanes(),
                                       int(q.kind),
                                       post=resolver._post_kc(known)))
            for f in futs:
                f.result()
            svc.end_window()

    drive(20)
    shapes_mid = set(svc.jit_shapes) | set(svc.index.jit_shapes)
    drive(20)
    shapes_end = set(svc.jit_shapes) | set(svc.index.jit_shapes)
    assert shapes_end == shapes_mid, \
        f"steady state must compile nothing new: {shapes_end - shapes_mid}"
    # absolute bound: row buckets × flat buckets × view tiers stays small
    assert len(shapes_end) <= 24, sorted(shapes_end)


# ---------------------------------------------------------------------------
# counter bookkeeping (one increment per SUBMITTED consult)
# ---------------------------------------------------------------------------

def test_device_consults_counted_per_consult_not_per_batch():
    store, resolver, _ = make_service_resolver()
    keys = [rk(i * 10) for i in range(6)]
    for i in range(20):
        register_both(store, resolver, tid(10 + i),
                      InternalStatus.PREACCEPTED, None, [keys[i % 6]])
    svc = resolver.service()
    before_consults = resolver.device_consults
    before_batches = svc.batches
    svc.begin_window()
    futs = [svc.submit([resolver.key_slot[keys[i % 6]]],
                       tid(1000 + i).as_timestamp().pack_lanes(), 0,
                       post=resolver._post_kc([keys[i % 6]]))
            for i in range(10)]
    futs[0].result()            # first demand dispatches the WHOLE window
    svc.end_window()
    assert resolver.device_consults - before_consults == 10, \
        "device_consults must count submitted consults, not launches"
    assert svc.batches - before_batches == 1
    assert all(f.done for f in futs)


def test_undemanded_window_costs_zero_launches():
    store, resolver, _ = make_service_resolver()
    key = rk(10)
    register_both(store, resolver, tid(10), InternalStatus.PREACCEPTED,
                  None, [key])
    svc = resolver.service()
    svc.begin_window()
    svc.submit([resolver.key_slot[key]], tid(99).as_timestamp().pack_lanes(),
               0, post=resolver._post_kc([key]))
    batches = svc.batches
    consults = resolver.device_consults
    svc.end_window()            # never demanded
    assert svc.batches == batches
    assert resolver.device_consults == consults
    assert svc.dropped_windows == 1


# ---------------------------------------------------------------------------
# burn-level byte-identity (zero observer effect of ENABLING the service)
# ---------------------------------------------------------------------------

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, max_tasks=3_000_000)


def _burn_trace(seed, **env_overrides):
    from cassandra_accord_tpu.config import LocalConfig
    # force the device tier so the service actually carries the consults
    # (at burn-scale indexes the auto cost model keeps everything on the
    # walk/host rungs — exactly the BENCH_r03 zero-consult shape)
    cfg = LocalConfig.from_env(resolver_kind="tpu", tpu_tier="device",
                               tpu_walk_max=0, tpu_walk_width=0,
                               **env_overrides)
    t = Trace()
    res = run_burn(seed, tracer=t.hook, resolver="tpu", batch_window_us=5000,
                   node_config=cfg, **HOSTILE)
    return t, res


def test_service_byte_identical_under_hostile_burn():
    """Same-seed hostile burn with the service OFF vs ON (deterministic host
    fallback): byte-identical message traces and outcomes — the service is a
    pure data-plane substitution."""
    ta, ra = _burn_trace(3, tpu_service="off")
    tb, rb = _burn_trace(3, tpu_service="on", tpu_service_backend="host")
    divergence = diff_traces(ta, tb)
    assert divergence is None, f"service changed the simulation:\n{divergence}"
    assert (ra.ops_ok, ra.ops_recovered, ra.ops_nacked, ra.ops_lost,
            ra.ops_failed, ra.sim_micros) == \
           (rb.ops_ok, rb.ops_recovered, rb.ops_nacked, rb.ops_lost,
            rb.ops_failed, rb.sim_micros)


def test_service_kernel_byte_identical_benign_burn():
    """Benign-network burn, forced device tier: service jax path vs legacy
    one-shot dispatch answer byte-identically (trace + outcomes)."""
    from cassandra_accord_tpu.config import LocalConfig
    base = dict(ops=30, concurrency=6, durability=True)
    traces = []
    results = []
    for service in ("off", "on"):
        cfg = LocalConfig.from_env(resolver_kind="tpu", tpu_tier="device",
                                   tpu_service=service,
                                   tpu_service_backend="jax",
                                   tpu_walk_max=0, tpu_walk_width=0)
        t = Trace()
        results.append(run_burn(21, tracer=t.hook, resolver="tpu",
                                batch_window_us=5000, node_config=cfg, **base))
        traces.append(t)
    divergence = diff_traces(*traces)
    assert divergence is None, f"service kernel diverged:\n{divergence}"
    a, b = results
    assert (a.ops_ok, a.sim_micros) == (b.ops_ok, b.sim_micros)
    # and the service actually carried consults on the protocol path
    assert b.stats.get("resolver_device_consults", 0) > 0
    assert b.stats.get("resolver_service_batches", 0) > 0
