"""Gray-failure nemesis suite: stop-the-world pauses, journal-append stalls,
journal corruption tolerance, and the adaptive timeout/backoff machinery.

Covers ISSUE 2: pause/resume with late-firing timers (PendingQueue idle
accounting staying exact — the PR-1 ``cancel()`` bug class, now for parked
tasks), disk stalls whose mid-stall crash loses the unsynced tail,
per-record checksums catching every injected bit flip, torn tails
truncating to the last whole record, the halt-loud vs quarantine-and-
bootstrap corrupt-record policies, exponential reply-timeout backoff with a
re-arm budget, slow-replica tracking feeding read speculation, the
``heal()`` reroll-task cancellation, and the burn CLI ``--json`` summary.
"""
import json
import os
from dataclasses import replace

import pytest

from cassandra_accord_tpu.config import LocalConfig
from cassandra_accord_tpu.harness.burn import SimulationException, run_burn
from cassandra_accord_tpu.harness.chaos import RandomizedLinkConfig
from cassandra_accord_tpu.harness.cluster import (
    Cluster, LinkConfig, SlowReplicaTracker, backoff_timeout_us)
from cassandra_accord_tpu.harness.journal import (
    Journal, JournalCorruption, Record)
from cassandra_accord_tpu.harness.watchdog import StallError, dump_wait_state
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.local.status import SaveStatus
from cassandra_accord_tpu.coordinate.tracking import ReadTracker
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topologies, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def make_cluster(seed=1, nodes=(1, 2, 3), link=None, progress_poll_s=0.2,
                 node_config=None, progress_log=True):
    shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    return Cluster(Topology(1, shards), seed=seed, link_config=link,
                   journal=True, progress_log=progress_log,
                   progress_poll_s=progress_poll_s, node_config=node_config)


def _exact_live(queue):
    return sum(1 for e in queue._heap if not e.cancelled and not e.recurring)


def gray_config(**overrides):
    return replace(LocalConfig(), **overrides)


# ---------------------------------------------------------------------------
# Pause: stop-the-world freeze, late-firing timers, exact idle accounting
# ---------------------------------------------------------------------------

def test_pause_freezes_timers_and_late_fires_at_resume():
    """A paused node's due timers park (in order) and fire at resume — not
    before, not dropped — and the queue's live accounting stays exact."""
    cluster = make_cluster(seed=1)
    fired = []
    cluster.nodes[3].scheduler.once(0.01, lambda: fired.append("a"))
    cluster.nodes[3].scheduler.once(0.02, lambda: fired.append("b"))
    cluster.pause(3)
    cluster.run_for(1.0)
    assert fired == [], "paused node's timers must not fire"
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)
    cluster.resume(3)
    cluster.run_for(0.1)
    assert fired == ["a", "b"], "parked timers must late-fire in park order"
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)


def test_cancel_while_parked_does_not_late_fire():
    """The pause analog of the PR-1 cancel() class: cancelling a timer whose
    guarded task already parked must prevent the late fire at resume (the
    queue entry is gone — only the holder flag can honor the cancel)."""
    cluster = make_cluster(seed=2)
    fired = []
    handle = cluster.nodes[3].scheduler.once(0.01, lambda: fired.append(1))
    cluster.pause(3)
    cluster.run_for(0.5)      # timer comes due, parks
    handle.cancel()
    cluster.resume(3)
    cluster.run_until_idle()
    assert fired == []
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)


def test_pause_resume_idle_accounting_stays_exact_across_cycles():
    """Seeded pause/resume cycles with timers landing before, inside and
    after each pause window: `_live_nonrecurring` equals the heap's exact
    live count at every phase boundary."""
    cluster = make_cluster(seed=3)
    rng = RandomSource(17)
    fired = []
    for cycle in range(12):
        victim = rng.pick([1, 2, 3])
        for _ in range(rng.next_int(1, 5)):
            cluster.nodes[victim].scheduler.once(
                rng.next_float() * 0.4, lambda: fired.append(1))
        cluster.pause(victim)
        cluster.run_for(rng.next_float() * 0.5)
        assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)
        cluster.resume(victim)
        cluster.run_for(rng.next_float() * 0.2)
        assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)
    cluster.run_until_idle()
    assert fired
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)


def test_paused_node_is_slow_not_dead():
    """With one replica paused the quorum still commits; after resume the
    paused node drains its parked deliveries and converges — no restart, no
    journal replay, exactly the regime fail-stop nemeses never exercise.
    (progress_log off: with it, a peer's recovery legitimately preempts the
    round racing the paused replica's timeout — tested in the burns.)"""
    cluster = make_cluster(seed=4, progress_log=False)
    cluster.pause(3)
    res = cluster.nodes[1].coordinate(list_txn([], {k(5): "while-paused"}))
    assert cluster.run_until(res.is_done, max_tasks=500_000)
    assert res.is_success(), res.failure
    cluster.resume(3)
    cluster.run_for(30)
    assert cluster.stores[3].get(k(5)) == ("while-paused",)


def test_crash_of_paused_node_drops_parked_tasks():
    """A paused process can die: its parked (already-popped) tasks die with
    it without corrupting idle accounting, and restart works normally."""
    cluster = make_cluster(seed=5)
    fired = []
    cluster.nodes[3].scheduler.once(0.01, lambda: fired.append(1))
    cluster.pause(3)
    cluster.run_for(0.5)
    cluster.crash(3)
    assert 3 not in cluster.paused
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)
    cluster.restart(3)
    cluster.run_until_idle()
    assert fired == []
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)


# ---------------------------------------------------------------------------
# Disk stall: durability (and sends) lag execution; crash loses the tail
# ---------------------------------------------------------------------------

def test_disk_stall_crash_loses_unsynced_tail_then_heals():
    """Writes land while node 3's journal is stalled (its packets are held —
    fsync-before-reply); a crash mid-stall loses every unsynced record, and
    the restarted node catches back up through bootstrap/deps."""
    cluster = make_cluster(seed=6)
    res = cluster.nodes[1].coordinate(list_txn([], {k(5): "pre"}))
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    pre_records = cluster.journal._live_count((3, 0))
    assert pre_records > 0
    cluster.stall_journal(3)
    res = cluster.nodes[1].coordinate(list_txn([], {k(5): "mid"}))
    assert cluster.run_until(res.is_done, max_tasks=500_000)
    cluster.run_for(5)
    assert cluster.journal._live_count((3, 0)) > pre_records, \
        "execution must keep appending records during the stall"
    cluster.crash(3)
    assert cluster.stats.get("journal_unsynced_lost", 0) > 0
    assert cluster.journal._live_count((3, 0)) == pre_records, \
        "crash mid-stall must rewind the journal to the stall watermark"
    cluster.restart(3)
    cluster.run_for(60)
    assert cluster.stores[3].get(k(5)) == ("pre", "mid")


def test_disk_stall_unstall_makes_everything_durable():
    """Unstall drains the held packets and fsyncs the buffer: a crash AFTER
    unstall loses nothing."""
    cluster = make_cluster(seed=7)
    cluster.stall_journal(3)
    res = cluster.nodes[1].coordinate(list_txn([], {k(9): "v"}))
    assert cluster.run_until(res.is_done, max_tasks=500_000)
    cluster.unstall_journal(3)
    cluster.run_for(10)
    records = cluster.journal._live_count((3, 0))
    cluster.crash(3)
    assert cluster.stats.get("journal_unsynced_lost", 0) == 0
    assert cluster.journal._live_count((3, 0)) == records
    cluster.restart(3)
    cluster.run_for(30)
    assert cluster.stores[3].get(k(9)) == ("v",)


def test_journal_stall_watermark_unit():
    """Unit contract: records appended after stall() are exactly what
    lose_unsynced() drops; pre-stall state survives."""
    from tests.test_restart import _applied_template, _clone_with_status
    from types import SimpleNamespace
    template = _applied_template()
    journal = Journal()
    store = SimpleNamespace(node=SimpleNamespace(id=4), id=0)
    journal.save(store, _clone_with_status(template, SaveStatus.STABLE))
    journal.stall(4)
    journal.save(store, _clone_with_status(template, SaveStatus.PRE_APPLIED))
    journal.save(store, _clone_with_status(template, SaveStatus.APPLIED))
    assert journal.is_stalled(4)
    lost = journal.lose_unsynced(4)
    assert lost == 2
    assert not journal.is_stalled(4)
    rebuilt = journal.restart_commands(4, 0)
    assert rebuilt[template.txn_id].save_status is SaveStatus.STABLE


# ---------------------------------------------------------------------------
# Journal integrity: checksums, torn tails, corruption policy
# ---------------------------------------------------------------------------

def _three_record_journal(node_id=9):
    """One txn journaled through three transitions => three records."""
    from tests.test_restart import _applied_template, _clone_with_status
    from types import SimpleNamespace
    template = _applied_template()
    journal = Journal()
    store = SimpleNamespace(node=SimpleNamespace(id=node_id), id=0)
    for status in (SaveStatus.ACCEPTED, SaveStatus.STABLE, SaveStatus.APPLIED):
        journal.save(store, _clone_with_status(template, status))
    recs = journal.logs[(node_id, 0)][template.txn_id]
    assert len(recs) == 3
    return journal, template.txn_id, recs


def test_checksum_catches_every_injected_bit_flip():
    """Property (seeded sweep): flipping ANY single bit of ANY record is
    detected at restart replay — a tail flip truncates as a torn write, a
    mid-log flip quarantines (or halts) — never a silent replay of damaged
    bytes.  CRC32 detects all single-bit errors, so this must be exhaustive
    over record choice and dense over bit positions."""
    rng = RandomSource(23)
    for case in range(120):
        journal, txn_id, recs = _three_record_journal()
        idx = rng.next_int(3)
        rec = recs[idx]
        nbits = len(rec.payload) * 8
        bit = rng.next_int(nbits)
        payload = bytearray(rec.payload)
        payload[bit // 8] ^= 1 << (bit % 8)
        rec.payload = bytes(payload)
        assert rec.try_diff() is None, \
            f"case {case}: bit {bit} of record {idx} not detected"
        replay = journal.restart_replay(9, 0, policy="quarantine")
        if idx == 2:
            # tail record: torn-write semantics — truncate, keep the prefix
            assert replay.torn_tail_dropped == 1
            assert replay.commands[txn_id].save_status is SaveStatus.STABLE
        else:
            assert replay.corrupt_records == 1
            assert txn_id in replay.quarantined
            assert txn_id not in replay.commands
            # quarantine scope: the txn's last-known route survives for the
            # bootstrap ladder
            assert replay.quarantined[txn_id] is not None


def test_mid_log_corruption_halts_loudly_under_halt_policy():
    journal, txn_id, recs = _three_record_journal()
    recs[0].payload = b"\x00" + recs[0].payload[1:]
    with pytest.raises(JournalCorruption):
        journal.restart_replay(9, 0, policy="halt")
    # restart_commands is the halt-policy shorthand
    journal2, _txn, recs2 = _three_record_journal()
    recs2[1].payload = recs2[1].payload[:-1] + b"\xff"
    with pytest.raises(JournalCorruption):
        journal2.restart_commands(9, 0)


def test_torn_tail_truncates_to_last_whole_record():
    """Property (seeded sweep): truncating the tail record at ANY cut point
    replays as if the torn append never happened."""
    rng = RandomSource(31)
    for _ in range(60):
        journal, txn_id, recs = _three_record_journal()
        tail = recs[2]
        cut = 1 + rng.next_int(len(tail.payload) - 1)
        tail.payload = tail.payload[:cut]
        replay = journal.restart_replay(9, 0, policy="halt")
        assert replay.torn_tail_dropped == 1
        assert replay.corrupt_records == 0
        # STABLE is the state the surviving prefix recorded
        assert replay.commands[txn_id].save_status is SaveStatus.STABLE


def test_tear_tail_record_injection_roundtrip():
    """The nemesis-facing injection helper tears the tail; replay truncates
    silently (no quarantine, no halt — normal WAL recovery)."""
    journal, txn_id, recs = _three_record_journal()
    assert journal.tear_tail_record(9, RandomSource(5)) == 1
    replay = journal.restart_replay(9, 0, policy="halt")
    assert replay.torn_tail_dropped == 1
    assert replay.commands[txn_id].save_status is SaveStatus.STABLE


def test_record_roundtrip_intact():
    rec = Record.encode({"save_status": {"$": "SaveStatus", "v": "STABLE",
                                         "e": 1}})
    assert rec.try_diff() == {"save_status": {"$": "SaveStatus",
                                              "v": "STABLE", "e": 1}}


def test_restart_quarantines_corrupt_record_and_converges():
    """End-to-end quarantine-and-bootstrap: a mid-log record of a crashed
    node's journal is corrupted; restart (policy=quarantine) drops the
    damaged txn, re-enters the catch-up ladder over its footprint, and the
    replica converges with its peers — no silent divergence, no halt."""
    cfg = gray_config(journal_corruption_policy="quarantine")
    cluster = make_cluster(seed=8, node_config=cfg)
    for i, value in enumerate(("a", "b", "c")):
        res = cluster.nodes[1].coordinate(list_txn([], {k(5): value}))
        assert cluster.run_until(res.is_done, max_tasks=500_000)
        assert res.is_success(), res.failure
    cluster.run_until_idle()
    cluster.crash(3)
    # corrupt a NON-tail record of some multi-record txn on node 3
    key = (3, 0)
    tail_txn = cluster.journal._tail_txn(key)
    target = None
    for txn_id, recs in cluster.journal.logs[key].items():
        if len(recs) >= 2 and txn_id != tail_txn:
            target = (txn_id, recs[0])
            break
    assert target is not None, "fixture needs a multi-record non-tail txn"
    txn_id, rec = target
    rec.payload = bytes([rec.payload[0] ^ 0x40]) + rec.payload[1:]
    cluster.restart(3)
    assert cluster.stats.get("journal_quarantined_txns", 0) >= 1
    cluster.run_for(90)
    datas = {n: cluster.stores[n].get(k(5)) for n in cluster.nodes}
    assert datas[3] == datas[1] == datas[2], f"divergent: {datas}"
    assert datas[1] == ("a", "b", "c")


def test_restart_halts_loudly_on_corrupt_record_under_halt_policy():
    cfg = gray_config(journal_corruption_policy="halt")
    cluster = make_cluster(seed=9, node_config=cfg)
    res = cluster.nodes[1].coordinate(list_txn([], {k(5): "x"}))
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    cluster.crash(3)
    key = (3, 0)
    tail_txn = cluster.journal._tail_txn(key)
    for txn_id, recs in cluster.journal.logs[key].items():
        if len(recs) >= 2 and txn_id != tail_txn:
            recs[0].payload = b"\x01" + recs[0].payload[1:]
            break
    else:
        pytest.skip("no multi-record non-tail txn in fixture")
    with pytest.raises(JournalCorruption):
        cluster.restart(3)


# ---------------------------------------------------------------------------
# Adaptive timeout/backoff + slow-replica tracking
# ---------------------------------------------------------------------------

def test_backoff_timeout_grows_capped_and_deterministic():
    base, factor, cap, jitter = 2.0, 2.0, 30.0, 0.25
    prev = 0
    for attempt in range(8):
        t = backoff_timeout_us(base, attempt, factor, cap, jitter, salt=42)
        # deterministic: same (salt, attempt) => same value
        assert t == backoff_timeout_us(base, attempt, factor, cap, jitter, 42)
        nominal = min(base * factor ** attempt, cap) * 1e6
        assert nominal <= t < nominal * (1 + jitter)
        assert t > prev or nominal == cap * 1e6
        prev = t
    # different salts de-phase (golden-ratio hash)
    assert backoff_timeout_us(base, 1, factor, cap, jitter, 1) \
        != backoff_timeout_us(base, 1, factor, cap, jitter, 2)


def test_reply_rearm_budget_bounds_patience():
    """Non-final replies re-arm the timeout with exponential backoff up to
    the budget; past it the LAST armed timer stands, so a lost final reply
    still fails the callback — bounded patience, never a hang."""
    from cassandra_accord_tpu.messages.base import Callback, Reply, Request

    class _NonFinal(Reply):
        is_final = False

    class _Probe(Request):
        def process(self, node, from_node, reply_context):
            pass

    cfg = gray_config(reply_rearm_budget=3)
    cluster = make_cluster(seed=10, node_config=cfg)
    cluster.request_filter = lambda *a: True   # swallow delivery entirely
    failures = []

    class _CB(Callback):
        def on_success(self, from_node, reply):
            pass

        def on_failure(self, from_node, failure):
            failures.append(failure)

        def on_callback_failure(self, from_node, failure):
            raise failure

    sink = cluster.sinks[1]
    sink.send_with_callback(2, _Probe(), _CB())
    (msg_id, entry), = sink.callbacks.items()
    assert entry[3] == 0
    # feed non-final replies: attempts advance only to the budget
    for expect in (1, 2, 2, 2):
        sink.deliver_reply(2, msg_id, _NonFinal())
        assert sink.callbacks[msg_id][3] == expect
    # the standing timer eventually fires the failure path
    cluster.run_until(lambda: bool(failures), max_tasks=100_000)
    assert failures and msg_id not in sink.callbacks
    # ... and the timeout marked the peer slow for the penalty window
    assert 2 in cluster.sinks[1].slow_replicas.slow_peers()


def test_slow_replica_tracker_marks_and_recovers():
    cluster = make_cluster(seed=11)
    tracker = SlowReplicaTracker(cluster, alpha=0.5, threshold_s=1.0,
                                 penalty_s=5.0)
    # fast replies: not slow
    tracker.record_reply(2, 10_000)
    assert not tracker.is_slow(2)
    # latency EWMA crossing the threshold marks slow
    for _ in range(6):
        tracker.record_reply(2, 3_000_000)
    assert tracker.is_slow(2)
    # recovery: fast replies decay the EWMA back under the threshold
    for _ in range(12):
        tracker.record_reply(2, 5_000)
    assert not tracker.is_slow(2)
    # a timeout penalizes for the window, then expires with sim time
    tracker.record_timeout(3)
    assert tracker.is_slow(3)
    cluster.queue.now_micros += 6_000_000
    assert not tracker.is_slow(3)


def test_read_tracker_routes_around_slow_replicas():
    shards = [Shard(Range(k(0), k(500)), [1, 2, 3]),
              Shard(Range(k(500), k(1000)), [3, 4, 5])]
    topo = Topologies([Topology(1, shards)])
    # initial picks avoid slow nodes when an alternative exists
    t = ReadTracker(topo)
    picks = t.initial_contacts(prefer=1, avoid=frozenset([1, 3]))
    assert 1 not in picks and 3 not in picks
    # all-slow shard: the base pick stands (avoidance must not starve)
    t2 = ReadTracker(topo)
    picks2 = t2.initial_contacts(prefer=1, avoid=frozenset([1, 2, 3, 4, 5]))
    assert picks2, "every shard still gets a read"
    # speculation prefers the non-slow untried candidate
    t3 = ReadTracker(topo)
    t3.initial_contacts(prefer=1)
    extra = t3.speculate(avoid=frozenset([2, 4]))
    assert extra and all(n not in (2, 4) for n in extra)


def test_paused_coordinator_timeout_late_fires_after_resume():
    """A paused node's own reply-timeout timers freeze with it: no spurious
    failure fires mid-pause; at resume the parked timeout runs and the
    failure path proceeds (gray failure seen from the INSIDE)."""
    from cassandra_accord_tpu.messages.base import Callback, Request

    class _Probe(Request):
        def process(self, node, from_node, reply_context):
            pass

    cluster = make_cluster(seed=12)
    cluster.request_filter = lambda *a: True
    failures = []

    class _CB(Callback):
        def on_success(self, from_node, reply):
            pass

        def on_failure(self, from_node, failure):
            failures.append(failure)

        def on_callback_failure(self, from_node, failure):
            raise failure

    cluster.sinks[1].send_with_callback(2, _Probe(), _CB())
    cluster.pause(1)
    cluster.run_for(10)      # way past the 2s base timeout
    assert failures == [], "a frozen process cannot observe its own timeout"
    cluster.resume(1)
    cluster.run_for(1)
    assert len(failures) == 1, "the parked timeout must late-fire at resume"


# ---------------------------------------------------------------------------
# Satellite 1: heal() cancels the chaos reroll task
# ---------------------------------------------------------------------------

def test_heal_cancels_chaos_reroll_task():
    link = RandomizedLinkConfig(RandomSource(3), rf=3, interval_s=0.5)
    cluster = make_cluster(seed=13, link=link)
    rolls = []
    orig = link.randomize
    link.randomize = lambda: (rolls.append(1), orig())[-1]
    cluster.run_for(2.0)
    assert rolls, "reroll cadence never fired"
    assert link._task is not None
    link.heal()
    count = len(rolls)
    cluster.run_for(5.0)
    assert len(rolls) == count, \
        "heal() must CANCEL the reroll task, not rely on the no-op guard"
    assert link._task is None


# ---------------------------------------------------------------------------
# Gray-failure burns (tier-1 smokes + determinism)
# ---------------------------------------------------------------------------

def _gray_cfg():
    # aggressive but STAGGERED cadences: the muted-quorum floor lets only
    # one node be down/paused/stalled at a time on a 3-replica cluster, so
    # the three axes must time-share the mute slot; short fault durations
    # keep it cycling
    return gray_config(
        restart_interval_s=0.5, restart_downtime_min_s=0.15,
        restart_downtime_max_s=0.4,
        pause_interval_s=0.35, pause_min_s=0.1, pause_max_s=0.35,
        disk_stall_interval_s=0.25, disk_stall_min_s=0.1, disk_stall_max_s=0.3)


def test_gray_failure_smoke_burn():
    """Fast tier-1 smoke: pause + disk-stall + crash-restart (with journal
    damage injection) all active on one burn; every op resolves, every fault
    class actually fired, final states agree."""
    result = run_burn(3, ops=60, concurrency=10, journal=True,
                      restart_nodes=True, pause_nodes=True, disk_stall=True,
                      node_config=_gray_cfg(), max_tasks=20_000_000)
    assert result.resolved == 60
    assert result.ops_failed == 0
    assert result.restarts >= 1, f"no crash-restart cycle: {result!r}"
    assert result.pauses >= 1, f"no pause cycle: {result!r}"
    assert result.disk_stalls >= 1, f"no disk stall: {result!r}"


def test_gray_failure_burn_is_deterministic():
    kw = dict(ops=50, concurrency=10, journal=True, restart_nodes=True,
              pause_nodes=True, disk_stall=True, node_config=_gray_cfg(),
              max_tasks=20_000_000)
    a = run_burn(5, **kw)
    b = run_burn(5, **kw)
    assert (a.ops_ok, a.ops_recovered, a.ops_nacked, a.ops_lost, a.crashes,
            a.restarts, a.pauses, a.disk_stalls, a.sim_micros) \
        == (b.ops_ok, b.ops_recovered, b.ops_nacked, b.ops_lost, b.crashes,
            b.restarts, b.pauses, b.disk_stalls, b.sim_micros)


def test_gray_failure_chaos_burn():
    """One hostile-network seed with all gray-failure axes in tier-1 (the
    full matrix is gated behind ACCORD_LONG_BURNS)."""
    cfg = gray_config(
        restart_interval_s=3.0, restart_downtime_min_s=1.0,
        restart_downtime_max_s=3.0, pause_interval_s=2.5,
        disk_stall_interval_s=3.5)
    # seed 4: with the round-9 trajectory (asym partitions draw extra rng;
    # reads no longer gate applies) this seed exercises restarts AND pauses
    result = run_burn(4, ops=60, concurrency=10, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      restart_nodes=True, pause_nodes=True, disk_stall=True,
                      node_config=cfg, max_tasks=40_000_000)
    assert result.resolved == 60
    assert result.pauses >= 1


def test_watchdog_dump_reports_gray_state():
    cluster = make_cluster(seed=14)
    cluster.pause(2)
    cluster.stall_journal(3)
    dump = dump_wait_state(cluster)
    assert "paused_nodes=[2]" in dump
    assert "stalled_journals=[3]" in dump
    cluster.resume(2)
    cluster.unstall_journal(3)


# ---------------------------------------------------------------------------
# Asymmetric partitions (one-way cuts, bridge partial partitions)
# ---------------------------------------------------------------------------

def test_asymmetric_partition_modes_unit():
    """Directed-drop semantics per mode: sym cuts both directions, oneway_out
    mutes the minority (it hears, cannot be heard), oneway_in deafens it,
    bridge lets exactly the bridge node talk to both sides."""
    link = RandomizedLinkConfig(RandomSource(1), rf=3)
    link._nodes = [1, 2, 3, 4, 5]
    link.partitioned = frozenset([1])
    for mode, out_drops, in_drops in (("sym", True, True),
                                      ("oneway_out", True, False),
                                      ("oneway_in", False, True)):
        link.partition_mode = mode
        assert link._partition_drops(1, 2) is out_drops, mode
        assert link._partition_drops(2, 1) is in_drops, mode
        # majority-internal links never drop
        assert not link._partition_drops(2, 3)
    link.partition_mode = "bridge"
    link.bridge = frozenset([3])
    assert link._partition_drops(1, 2) and link._partition_drops(2, 1)
    assert not link._partition_drops(1, 3) and not link._partition_drops(3, 1)
    assert not link._partition_drops(3, 2)
    # healed clears everything
    link.heal()
    assert link.action(1, 2) == LinkConfig.DELIVER


def test_asymmetric_partitions_randomize_deterministically():
    """The asym coin and mode picks ride the seeded rng: same seed, same
    sequence of (partitioned, mode, bridge) draws — and at least one asym
    mode actually occurs across the re-rolls for a coin-friendly seed."""
    def roll(seed, n=40):
        link = RandomizedLinkConfig(RandomSource(seed), rf=5)
        link._nodes = list(range(1, 8))
        out = []
        for _ in range(n):
            link.randomize()
            out.append((link.partitioned, link.partition_mode, link.bridge))
        return out

    a, b = roll(3), roll(3)
    assert a == b, "asym partition draws must be seed-deterministic"
    modes = {m for _p, m, _b in a}
    assert modes - {"sym"}, f"no asymmetric mode in 40 re-rolls: {modes}"


def test_hostile_burn_with_asymmetric_partitions():
    """A chaos burn whose seed draws asymmetric partitions still resolves
    every op (the adaptive-timeout + speculation machinery absorbs one-way
    silence like it absorbs pauses)."""
    result = run_burn(3, ops=60, concurrency=10, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      max_tasks=40_000_000)
    assert result.resolved == 60


# ---------------------------------------------------------------------------
# Satellite 5: burn CLI --json summary
# ---------------------------------------------------------------------------

def test_burn_cli_json_summary(monkeypatch, tmp_path):
    from cassandra_accord_tpu.harness import burn as burn_mod

    class _FakeResult:
        seed = 0
        ops_ok = 4
        ops_recovered = 1
        ops_nacked = 0
        ops_lost = 0
        ops_failed = 0
        resolved = 5
        sim_micros = 1_234_000
        stats = {"node_crashes": 2, "node_restarts": 2, "node_pauses": 3,
                 "journal_stalls": 1, "journal_injected_tears": 1}

        def __repr__(self):
            return "BurnResult(fake)"

    monkeypatch.setattr(burn_mod, "run_burn",
                        lambda seed, **kw: _FakeResult())
    path = tmp_path / "summary.json"
    burn_mod.main(["--seeds", "0", "--ops", "5", "--json", str(path)])
    doc = json.loads(path.read_text())
    (entry,) = doc["results"]
    assert entry["status"] == "pass"
    assert entry["resolved"] == 5 and entry["recovered"] == 1
    assert entry["faults"] == {"node_crashes": 2, "node_restarts": 2,
                               "node_pauses": 3, "journal_stalls": 1,
                               "journal_injected_tears": 1}
    assert "wall_s" in entry and entry["sim_ms"] == 1234


def test_burn_cli_json_records_stall(monkeypatch, tmp_path):
    from cassandra_accord_tpu.harness import burn as burn_mod

    def fake_run_burn(seed, **kw):
        raise SimulationException(seed, StallError("no progress for 120.0s",
                                                   "BLOCKED [1,42,1]Wk"))
    monkeypatch.setattr(burn_mod, "run_burn", fake_run_burn)
    path = tmp_path / "summary.json"
    with pytest.raises(SystemExit) as exc:
        burn_mod.main(["--seeds", "7", "--ops", "5", "--json", str(path)])
    assert exc.value.code == 2
    doc = json.loads(path.read_text())
    (entry,) = doc["results"]
    assert entry["seed"] == 7 and entry["status"] == "stall"
    assert "no progress" in entry["error"]


# ---------------------------------------------------------------------------
# The seed-6 range-read vs bootstrap-refencing wedge: FIXED — promoted from
# gated xfail to a tier-1 regression test (round 9).  The fix family:
# grandfathered partial-read coverage (monotone union across retry rounds +
# per-command unresolved-elision tracking at the serve), the MVCC read-dep
# rule (nothing waits on a read's local apply), re-fencing backoff under
# slo.unapplied pressure, and the churn clean-quorum floor.
# ---------------------------------------------------------------------------

def test_seed6_range_read_refencing_regression():
    """The exact KNOWN_ISSUES repro (burn CLI: --seeds 6 --ops 200
    --no-restart) that wedged from PR 1 through PR 6: every wait chain
    rooted on a range read that could never assemble partial-read coverage
    while the truncation/staleness ladder re-fenced the ranges.  Must now
    resolve all 200 ops with no watchdog fire."""
    cfg = LocalConfig.from_env()
    rf = 2 + RandomSource(6).next_int(8)
    result = run_burn(6, ops=200, concurrency=20, rf=rf, chaos=True,
                      allow_failures=True, topology_churn=True,
                      durability=True, journal=True, delayed_stores=True,
                      clock_drift=True, cache_miss=True, restart_nodes=False,
                      node_config=cfg,
                      stall_watchdog_s=cfg.stall_watchdog_after_s,
                      max_tasks=200_000_000)
    assert result.resolved == 200, result


# ---------------------------------------------------------------------------
# Acceptance: the gray-failure x hostile matrix (gated)
# ---------------------------------------------------------------------------

@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="seed-range gray-failure matrix; run with ACCORD_LONG_BURNS=1")
def test_gray_failure_hostile_matrix_seed_range():
    """Seeds 0-9 — NO carve-outs (the seed-6 refencing wedge is fixed,
    round 9) — x 250 ops with pause + disk-stall + crash-restart (journal
    damage injection active, quarantine policy) alongside the full hostile
    matrix: all resolve, final states reconcile, zero silent replica
    divergence.

    Default cadences (restart 20s / pause 15s / disk-stall 17s): the three
    independent axes COMBINE into roughly the fault rate PR-1's single-axis
    5s matrix injected.  Tripling all three (restart at 5s with pause+stall
    active) outruns the bootstrap heal rate into expected unavailability —
    overload, not a protocol bug."""
    cfg = gray_config()
    fault_totals = {"restarts": 0, "pauses": 0, "stalls": 0}
    for seed in range(10):
        rf = 2 + RandomSource(seed).next_int(8)
        result = run_burn(seed, ops=250, concurrency=20, rf=rf, chaos=True,
                          allow_failures=True, topology_churn=True,
                          durability=True, journal=True, delayed_stores=True,
                          clock_drift=True, cache_miss=True,
                          restart_nodes=True, pause_nodes=True,
                          disk_stall=True, node_config=cfg,
                          stall_watchdog_s=300.0, max_tasks=200_000_000)
        assert result.resolved == 250, result
        fault_totals["restarts"] += result.restarts
        fault_totals["pauses"] += result.pauses
        fault_totals["stalls"] += result.disk_stalls
    # every axis must actually engage across the range (the aggressive
    # per-axis cadences are exercised by the tier-1 smokes; here the point
    # is convergence with all axes live at the sustainable combined rate —
    # measured 2026-08-02: 3 restarts / 8 pauses / 7 stalls over the range)
    for axis, total in fault_totals.items():
        assert total >= 1, (axis, fault_totals)
