"""Mesh-sharded data plane vs single-device reference: results must be
bit-identical (the collectives only reorganize the same computation)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cassandra_accord_tpu import ops, parallel
from cassandra_accord_tpu.models import TxnBatch, txn_step
from cassandra_accord_tpu.ops import graph_state as gs
from cassandra_accord_tpu.primitives.timestamp import TxnId, TxnKind, Domain

T, K, B = 64, 32, 16  # T divisible by 8 devices

# the mesh tests shard over 8 devices (conftest requests 8 virtual CPU
# devices via XLA_FLAGS; a pre-initialized jax or an overriding environment
# can leave fewer) — skip with the reason instead of failing on environment
needs_8_devices = pytest.mark.skipif(
    jax.device_count() < 8,
    reason=f"needs 8 JAX devices for the sharding mesh, "
           f"have {jax.device_count()} (conftest's virtual-device request "
           f"did not take effect in this environment)")


def _batch(rng, base_hlc, slots):
    key_inc = np.zeros((B, K), dtype=np.int8)
    kinds = np.zeros(B, dtype=np.int8)
    lanes = np.zeros((B, gs.TS_LANES), dtype=np.int32)
    for i in range(B):
        key_inc[i, rng.choice(K, rng.integers(1, 5), replace=False)] = 1
        kind = TxnKind(rng.choice([0, 1, 3, 4]))
        tid = TxnId(epoch=1, hlc=base_hlc + int(rng.integers(0, 200)),
                    node=int(rng.integers(1, 8)), kind=kind, domain=Domain.KEY)
        kinds[i] = int(kind)
        lanes[i] = tid.pack_lanes()
    return TxnBatch(
        slots=jnp.asarray(slots, dtype=jnp.int32),
        key_inc=jnp.asarray(key_inc),
        txn_id=jnp.asarray(lanes),
        kind=jnp.asarray(kinds),
        valid=jnp.ones((B,), dtype=jnp.bool_))


@needs_8_devices
def test_sharded_step_matches_single_device():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    rng = np.random.default_rng(3)
    mesh = parallel.make_mesh(8)
    step = parallel.build_sharded_step(mesh)

    single = ops.init_state(T, K)
    sharded = parallel.shard_state(ops.init_state(T, K), mesh)

    for round_i in range(3):
        slots = np.arange(round_i * B, (round_i + 1) * B)
        batch = _batch(np.random.default_rng(100 + round_i),
                       1000 * (round_i + 1), slots)
        single, deps_s, applied_s = txn_step(single, batch)
        sharded, cmax_m, applied_m = step(sharded, batch)
        assert (np.asarray(applied_s) == np.asarray(applied_m)).all(), round_i

    for name in gs.GraphState._fields:
        a, b = getattr(single, name), getattr(sharded, name)
        assert (np.asarray(a) == np.asarray(b)).all(), name


@needs_8_devices
def test_sharded_closure_matches():
    rng = np.random.default_rng(5)
    adj = np.tril(rng.random((T, T)) < 0.08, k=-1).astype(np.int8)
    mesh = parallel.make_mesh(8)
    closure = parallel.build_sharded_closure(mesh)
    got = np.asarray(closure(jnp.asarray(adj)))
    want = np.asarray(ops.transitive_closure(jnp.asarray(adj)))
    assert (got == want).all()


@needs_8_devices
def test_sharded_store_consult_matches_single_device():
    """The PROTOCOL plane over the mesh: per-store consults sharded one store
    per device + cross-store timestamp-proposal reduce must equal running the
    same consults store-by-store on one device."""
    from cassandra_accord_tpu.ops import deps_kernels as dk
    S, Ts, Ks, Bq = 8, 16, 8, 4
    rng = np.random.default_rng(17)
    key_inc = (rng.random((S, Ts, Ks)) < 0.3).astype(np.int8)
    ts = np.zeros((S, Ts, 5), dtype=np.int32)
    ts[..., 0] = 1
    ts[..., 2] = rng.integers(1, 1000, (S, Ts))
    ts[..., 4] = rng.integers(1, 8, (S, Ts))
    txn_id = ts.copy()
    kind = rng.integers(0, 2, (S, Ts)).astype(np.int8)
    status = rng.integers(1, 6, (S, Ts)).astype(np.int8)
    active = np.ones((S, Ts), dtype=bool)
    q = (rng.random((S, Bq, Ks)) < 0.3).astype(np.int8)
    before = np.zeros((S, Bq, 5), dtype=np.int32)
    before[..., 0] = 1
    before[..., 2] = 2000
    qkind = rng.integers(0, 2, (S, Bq)).astype(np.int8)

    mesh = parallel.make_mesh(8)
    consult = parallel.build_sharded_store_consult(mesh)
    deps_m, gmax = consult(*(jnp.asarray(x) for x in (
        key_inc, key_inc, ts, txn_id, kind, status, active, q, before, qkind)))

    # single-device reference: consult each store, lex-max across stores
    singles = [dk.consult(*(jnp.asarray(x[s]) for x in (
        key_inc, key_inc, ts, txn_id, kind, status, active, q, before, qkind)))
        for s in range(S)]
    for s in range(S):
        assert (np.asarray(deps_m[s]) == np.asarray(singles[s][0])).all(), s
    stack = np.stack([np.asarray(m) for _, m in singles])   # [S, B, 5]
    want = np.zeros((Bq, 5), dtype=np.int64)
    tie = np.ones((S, Bq), dtype=bool)
    for lane in range(5):
        v = np.where(tie, stack[..., lane], -1)
        best = v.max(axis=0)
        tie = tie & (stack[..., lane] == best[None, :])
        want[:, lane] = np.maximum(best, 0)
    assert (np.asarray(gmax) == want).all()


@needs_8_devices
def test_sharded_frontier_matches():
    from cassandra_accord_tpu.ops import deps_kernels as dk
    S, Ts = 8, 16
    rng = np.random.default_rng(23)
    adj = (rng.random((S, Ts, Ts)) < 0.15).astype(np.int8)
    status = rng.integers(1, 7, (S, Ts)).astype(np.int8)
    active = rng.random((S, Ts)) < 0.9
    mesh = parallel.make_mesh(8)
    frontier = parallel.build_sharded_frontier(mesh)
    got = np.asarray(frontier(jnp.asarray(adj), jnp.asarray(status),
                              jnp.asarray(active)))
    for s in range(S):
        want = np.asarray(dk.kahn_frontier(
            jnp.asarray(adj[s]), jnp.asarray(status[s]), jnp.asarray(active[s])))
        assert (got[s] == want).all(), s


@needs_8_devices
def test_live_state_sharded_consult_parity():
    """The live-state multichip path (parallel/live_dryrun.py): a real burn
    builds every store's device index; the burn's own recorded consults are
    answered by the mesh-sharded kernel with parity vs single-device."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from cassandra_accord_tpu import parallel
    from cassandra_accord_tpu.ops import deps_kernels as dk
    from cassandra_accord_tpu.parallel import live_dryrun as ld

    n = 4
    mesh = parallel.make_mesh(devices=jax.devices()[:n])
    stores, recorder, _snaps = ld.collect_live_state(n, seed=11, ops=40)
    assert len(stores) == n
    st = ld.stack_store_indexes(stores)
    assert st["active"].any()
    q, before, qkind, n_real = ld.build_query_batches(stores, recorder,
                                                      st["key_inc"].shape[2])
    assert n_real > 0
    args = (st["live_inc"], st["key_inc"], st["ts"], st["txn_id"], st["kind"],
            st["status"], st["active"], q, before, qkind)
    consult = parallel.build_sharded_store_consult(mesh)
    deps, gmax = consult(*(jnp.asarray(x) for x in args))
    deps1, _ = jax.vmap(dk.consult)(*(jnp.asarray(x) for x in args))
    assert np.array_equal(np.asarray(deps), np.asarray(deps1))
    assert np.asarray(gmax).shape == (q.shape[1], 5)
