"""Sim-time windowed telemetry (observe/timeline.py) and its contracts:

1. ZERO OBSERVER EFFECT, extended: a same-seed hostile burn with timelines +
   burn-rate monitors attached vs a bare run yields byte-identical full
   message traces and identical outcomes — the PR-3 proof, re-proven for the
   trajectory plane.
2. EXACT WINDOWED PERCENTILES: every window's p50/p95/p99 equals the
   nearest-rank percentile recomputed independently from the recorded span
   latencies falling in that window, and the window counts partition the
   whole-run registry histogram exactly.
3. POLICY ENFORCEMENT: every metric feeds only under its declared
   ``TIMELINE_POLICIES`` verb; excluded/undeclared metrics raise.
"""
import json
import math

import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.observe import (BurnRateMonitor, FlightRecorder,
                                          Timeline, commits_per_sec_series,
                                          exact_percentile,
                                          validate_chrome_trace)
from cassandra_accord_tpu.observe import schema
from cassandra_accord_tpu.observe.timeline import (service_window_records,
                                                   write_timeline_jsonl)

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)


def _nearest_rank(values, q):
    """Independent nearest-rank percentile (the test's own formula)."""
    vals = sorted(values)
    if not vals:
        return None
    return vals[min(max(1, math.ceil(q * len(vals))), len(vals)) - 1]


# ---------------------------------------------------------------------------
# the zero-observer-effect proof, extended to timelines + burn-rate monitors
# ---------------------------------------------------------------------------

def test_zero_observer_effect_timeline_and_burnrate_hostile():
    """Same-seed hostile burn: bare vs (timeline + burn-rate monitors)
    attached — byte-identical full message traces, identical outcomes."""
    ta, tb = Trace(), Trace()
    bare = run_burn(9, tracer=ta.hook, **HOSTILE)
    rec = FlightRecorder(timeline=Timeline(window_us=500_000),
                         burnrate=BurnRateMonitor())
    observed = run_burn(9, tracer=tb.hook, observer=rec, **HOSTILE)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"timeline/burnrate perturbed the simulation:\n{divergence}"
    assert (bare.ops_ok, bare.ops_recovered, bare.ops_nacked, bare.ops_lost,
            bare.ops_failed, bare.sim_micros) == \
           (observed.ops_ok, observed.ops_recovered, observed.ops_nacked,
            observed.ops_lost, observed.ops_failed, observed.sim_micros)
    # and the trajectory plane actually recorded something
    assert rec.timeline.records(), "no telemetry windows recorded"


# ---------------------------------------------------------------------------
# windowed percentiles: exact, cross-checked against the span latencies
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def windowed_burn():
    tl = Timeline(window_us=1_000_000)
    rec = FlightRecorder(timeline=tl)
    res = run_burn(5, ops=120, concurrency=12, journal=True, durability=True,
                   observer=rec)
    return rec, tl, res


def test_windowed_percentiles_match_exact_recompute(windowed_burn):
    """Per window: the reported latency p50/p95/p99 equals the nearest-rank
    percentile of the span latencies resolved inside that window, computed
    independently here."""
    rec, tl, _res = windowed_burn
    by_window = {}
    for span in rec.spans.client_spans():
        if span.resolved_us is None:
            continue
        idx = span.resolved_us // tl.window_us
        by_window.setdefault(idx, []).append(
            span.resolved_us - span.submitted_us)
    checked = 0
    for r in tl.records():
        pct = r["scopes"].get("cluster", {}).get("percentiles", {}) \
            .get(schema.LATENCY_METRIC)
        if pct is None:
            continue
        expected = by_window.get(r["window"], [])
        assert pct["count"] == len(expected)
        for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            assert pct[key] == _nearest_rank(expected, q), \
                f"window {r['window']} {key} mismatch"
        assert pct["max"] == max(expected)
        checked += 1
    assert checked >= 1, "no window carried latency percentiles"


def test_window_counts_partition_whole_run_histogram(windowed_burn):
    """The per-window latency counts sum exactly to the whole-run registry
    histogram's count, and each window's exact p99 is consistent with the
    histogram's conservative bucket-upper-bound estimate (exact <= bound
    whenever the bound exists)."""
    rec, tl, res = windowed_burn
    hist = rec.registry.histogram(schema.LATENCY_METRIC)
    window_total = sum(
        r["scopes"]["cluster"]["percentiles"][schema.LATENCY_METRIC]["count"]
        for r in tl.records()
        if schema.LATENCY_METRIC
        in r["scopes"].get("cluster", {}).get("percentiles", {}))
    assert window_total == hist.count == res.resolved
    # whole-run exact percentile vs the registry's conservative bucket bound
    latencies = sorted(s.resolved_us - s.submitted_us
                       for s in rec.spans.client_spans()
                       if s.resolved_us is not None)
    for q in (0.5, 0.95, 0.99):
        bound = hist.percentile(q)
        if bound is not None:
            assert exact_percentile(latencies, q) <= bound


def test_windowed_rates_partition_registry_counters(windowed_burn):
    """Summed per-window counts equal the registry's whole-run counters for
    the submitted/resolved streams (the commits/s series is a partition of
    the run, not a resample)."""
    rec, tl, res = windowed_burn
    recs = tl.records()
    submitted = sum(
        r["scopes"]["cluster"].get("counts", {}).get(schema.SUBMITTED_METRIC, 0)
        for r in recs)
    assert submitted == rec.registry.counter(schema.SUBMITTED_METRIC).value \
        == res.ops_submitted
    series = commits_per_sec_series(recs)
    assert series, "no commits/s windows"
    window_s = tl.window_us / 1e6
    commits_from_series = round(sum(v for _w, v in series) * window_s)
    assert commits_from_series == res.ops_ok + res.ops_recovered


def test_node_and_store_scopes_recorded(windowed_burn):
    _rec, tl, _res = windowed_burn
    scopes = set()
    for r in tl.records():
        scopes.update(r["scopes"])
    assert "cluster" in scopes
    assert any(s.startswith("node/") for s in scopes)
    assert any(s.startswith("store/") for s in scopes)


# ---------------------------------------------------------------------------
# ring bound + policy enforcement
# ---------------------------------------------------------------------------

def test_ring_bound_keeps_last_windows():
    tl = Timeline(window_us=1_000, keep_windows=10)
    for i in range(50):
        tl.count("txn.submitted", now_us=i * 1_000)
    recs = tl.records(include_open=False)
    assert len(recs) == 10
    assert tl.dropped_windows == 39   # 49 finalized, 10 kept
    assert recs[-1]["window"] == 48   # the open window (49) is not finalized
    assert recs[0]["window"] == 39


def test_policy_enforced_at_feed_time():
    tl = Timeline()
    # wrong verb: a rate metric fed as a sample
    with pytest.raises(ValueError, match="TIMELINE_POLICIES"):
        tl.sample("txn.submitted", 1, now_us=0)
    # excluded metrics refuse every verb
    excluded = schema.RESOLVER_METRICS["device_consults"]
    with pytest.raises(ValueError, match="excluded"):
        tl.count(excluded, now_us=0)
    # undeclared metrics raise actionably (the lint contract, live)
    with pytest.raises(KeyError, match="TIMELINE_POLICIES"):
        tl.count("bogus.metric", now_us=0)


def test_exact_percentile_nearest_rank():
    vals = list(range(1, 101))
    assert exact_percentile(vals, 0.50) == 50
    assert exact_percentile(vals, 0.95) == 95
    assert exact_percentile(vals, 0.99) == 99
    assert exact_percentile([7], 0.99) == 7
    assert exact_percentile([], 0.5) is None


# ---------------------------------------------------------------------------
# export surfaces: JSONL artifact + Perfetto counter track
# ---------------------------------------------------------------------------

def test_timeline_jsonl_artifact(tmp_path, windowed_burn):
    rec, tl, _res = windowed_burn
    path = tmp_path / "timeline.jsonl"
    write_timeline_jsonl(str(path), rec)
    lines = path.read_text().strip().splitlines()
    header = json.loads(lines[0])
    assert header["kind"] == "header"
    assert header["schema"] == "accord-timeline/1"
    assert header["window_us"] == tl.window_us
    windows = [json.loads(l) for l in lines[1:]]
    telemetry = [w for w in windows if "scopes" in w]
    assert len(telemetry) == header["windows"]
    assert all(w["end_us"] - w["start_us"] == tl.window_us for w in telemetry)


def test_perfetto_timeline_counter_track(windowed_burn):
    rec, _tl, _res = windowed_burn
    doc = rec.chrome_trace()
    assert validate_chrome_trace(doc) == []
    track = [e for e in doc["traceEvents"]
             if e.get("ph") == "C" and e.get("pid") == 0 and e.get("tid") == 2]
    assert track, "timeline counter track missing"
    assert any("commits_per_sec" in e["args"] for e in track)
    assert any("latency_p99_ms" in e["args"] for e in track)
    named = [e for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"
             and e["pid"] == 0 and e["tid"] == 2]
    assert named and named[0]["args"]["name"] == "timeline"


def test_service_window_records_from_samples():
    """Consult-service trajectory windows derived from deterministic
    (ts, depth, rows) samples — bucketed, max/mean per window."""
    class _Rec:
        _service_samples = [(100, 2, 8), (900, 5, 16), (1_500, 1, 4),
                            (2_200, 3, 32)]
    recs = service_window_records(_Rec(), window_us=1_000)
    assert [r["window"] for r in recs] == [0, 1, 2]
    assert recs[0]["queue_depth_max"] == 5
    assert recs[0]["batch_rows_max"] == 16
    assert recs[0]["dispatches"] == 2
    assert recs[0]["batch_rows_mean"] == 12.0
    assert all(r["kind"] == "service_window" for r in recs)
