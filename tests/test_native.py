"""Native C++ consult engine: build, parity vs the numpy host tier and the
device kernel, and engagement on the protocol path."""
import numpy as np
import pytest

from cassandra_accord_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain to build the native lib")


def _random_state(rng, T, K):
    h = {
        "key_inc": (rng.random((T, K)) < 0.3).astype(np.int8),
        "ts": np.zeros((T, 5), dtype=np.int32),
        "txn_id": np.zeros((T, 5), dtype=np.int32),
        "kind": rng.integers(0, 2, T).astype(np.int8),
        "status": rng.integers(1, 7, T).astype(np.int8),
        "active": rng.random(T) < 0.9,
    }
    # live = full minus random covered bits (elision)
    h["live_inc"] = (h["key_inc"] & (rng.random((T, K)) < 0.8)).astype(np.int8)
    h["ts"][:, 0] = 1
    h["ts"][:, 2] = rng.integers(1, 5000, T)
    h["ts"][:, 4] = rng.integers(1, 9, T)
    h["txn_id"][:, 0] = 1
    h["txn_id"][:, 2] = rng.integers(1, 5000, T)
    h["txn_id"][:, 4] = rng.integers(1, 9, T)
    return h


def _numpy_reference(h, qcols, before, kind, invalidated):
    """The numpy host tier's math, straight from _consult_host."""
    from cassandra_accord_tpu.primitives.timestamp import TxnKind
    T, K = h["key_inc"].shape
    B = len(qcols)
    q = np.zeros((B, K), dtype=np.int8)
    for i, cols in enumerate(qcols):
        q[i, cols] = 1

    def lex_less(a, b):
        lt = a[..., 4] < b[..., 4]
        for lane in range(3, -1, -1):
            lt = (a[..., lane] < b[..., lane]) \
                | ((a[..., lane] == b[..., lane]) & lt)
        return lt

    wit = np.zeros((len(TxnKind), len(TxnKind)), dtype=bool)
    for a in TxnKind:
        for b2 in TxnKind:
            wit[a, b2] = a.witnesses(b2)
    share_live = (q.astype(np.float32) @ h["live_inc"].T.astype(np.float32)) > 0
    started = lex_less(h["txn_id"][None, :, :], before[:, None, :])
    w = wit[kind[:, None].astype(np.int64), h["kind"][None, :].astype(np.int64)]
    eligible = h["active"] & (h["status"] != invalidated)
    deps = share_live & started & w & eligible[None, :]
    share_full = (q.astype(np.float32) @ h["key_inc"].T.astype(np.float32)) > 0
    mc = share_full & h["active"][None, :]
    per_slot = np.where(lex_less(h["ts"], h["txn_id"])[:, None],
                        h["txn_id"], h["ts"])
    tie = mc.copy()
    out = np.zeros((B, 5), dtype=np.int64)
    for lane in range(5):
        vals = np.where(tie, per_slot[None, :, lane], -1)
        best = vals.max(axis=1)
        tie = tie & (per_slot[None, :, lane] == best[:, None])
        out[:, lane] = np.maximum(best, 0)
    return deps, out


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_parity_vs_numpy(seed):
    rng = np.random.default_rng(seed)
    T, K, B = 96, 24, 12
    h = _random_state(rng, T, K)
    qcols = [sorted(rng.choice(K, rng.integers(1, 4), replace=False).tolist())
             for _ in range(B)]
    before = np.zeros((B, 5), dtype=np.int32)
    before[:, 0] = 1
    before[:, 2] = rng.integers(1, 6000, B)
    before[:, 4] = rng.integers(1, 9, B)
    kind = rng.integers(0, 2, B).astype(np.int8)
    from cassandra_accord_tpu.ops.graph_state import INVALIDATED
    deps_n, max_n = native.consult_batch(h, qcols, before, kind, INVALIDATED)
    deps_r, max_r = _numpy_reference(h, qcols, before, kind, INVALIDATED)
    assert np.array_equal(deps_n, deps_r)
    assert np.array_equal(max_n, max_r)


def test_engages_on_protocol_burn(monkeypatch):
    """A burn above the walk tier must route sparse consults to the native
    engine and stay green (parity with the walk asserted by resolver=verify)."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    # the narrow-query walk routing would (correctly) claim these sparse
    # consults in production; pin it off to keep the native engine under test
    monkeypatch.setenv("ACCORD_TPU_WALK_WIDTH", "0")
    from cassandra_accord_tpu.harness.burn import run_burn
    result = run_burn(seed=511, ops=60, concurrency=8, resolver="verify")
    assert result.ops_ok == 60
    assert result.stats.get("resolver_native_consults", 0) > 0, \
        "native engine never engaged on the protocol path"


def test_want_flags():
    rng = np.random.default_rng(9)
    h = _random_state(rng, 32, 8)
    qcols = [[0, 1]]
    before = np.full((1, 5), 9999, dtype=np.int32)
    kind = np.zeros(1, dtype=np.int8)
    from cassandra_accord_tpu.ops.graph_state import INVALIDATED
    deps, mx = native.consult_batch(h, qcols, before, kind, INVALIDATED,
                                    want_max=False)
    assert mx is None and deps is not None
    deps, mx = native.consult_batch(h, qcols, before, kind, INVALIDATED,
                                    want_deps=False)
    assert deps is None and mx is not None
