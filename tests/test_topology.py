"""Shard quorum math, Topology selection, TopologyManager epoch ledger.

Parity targets: Shard.java:38-90 quorum formulas, TopologyManagerTest (:1-584).
"""
import pytest

from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.route import Route
from cassandra_accord_tpu.primitives.keys import RoutingKeys
from cassandra_accord_tpu.topology import Shard, Topologies, Topology, TopologyManager


def k(v):
    return IntKey(v)


def r(a, b):
    return Range(k(a), k(b))


def test_shard_quorum_math():
    # formulas from Shard.java:71-90
    s3 = Shard(r(0, 100), [1, 2, 3])
    assert s3.max_failures == 1
    assert s3.slow_path_quorum_size == 2
    assert s3.fast_path_quorum_size == (1 + 3) // 2 + 1 == 3
    assert s3.recovery_fast_path_size == 1

    s5 = Shard(r(0, 100), [1, 2, 3, 4, 5])
    assert s5.max_failures == 2
    assert s5.slow_path_quorum_size == 3
    assert s5.fast_path_quorum_size == (2 + 5) // 2 + 1 == 4

    # smaller electorate lowers the fast-path quorum
    s5e = Shard(r(0, 100), [1, 2, 3, 4, 5], fast_path_electorate=[1, 2, 3])
    assert s5e.fast_path_quorum_size == (2 + 3) // 2 + 1 == 3
    # electorate must include at least n-f nodes
    with pytest.raises(ValueError):
        Shard(r(0, 100), [1, 2, 3, 4, 5], fast_path_electorate=[1, 2])


def test_rejects_fast_path():
    s = Shard(r(0, 100), [1, 2, 3])  # fp quorum 3 of electorate 3
    assert not s.rejects_fast_path(0)
    assert s.rejects_fast_path(1)


def test_topology_lookup_and_views():
    t = Topology(1, [Shard(r(0, 10), [1, 2, 3]), Shard(r(10, 20), [2, 3, 4])])
    assert t.for_key(k(5)).nodes == (1, 2, 3)
    assert t.for_key(k(10)).nodes == (2, 3, 4)
    assert t.for_key(k(25)) is None
    assert t.nodes() == {1, 2, 3, 4}
    assert t.ranges_for_node(1) == Ranges.of(r(0, 10))
    assert t.ranges_for_node(3) == Ranges.of(r(0, 20))
    sel = t.for_selection(RoutingKeys.of([k(5), k(15)]))
    assert len(sel) == 2
    assert t.nodes_for(Ranges.of(r(0, 5))) == [1, 2, 3]
    route = Route.for_keys(k(5), RoutingKeys.of([k(5)]))
    assert t.nodes_for(route) == [1, 2, 3]


def test_topology_rejects_overlapping_shards():
    with pytest.raises(ValueError):
        Topology(1, [Shard(r(0, 10), [1]), Shard(r(5, 15), [2])])


def test_topologies_stack():
    t1 = Topology(1, [Shard(r(0, 10), [1, 2, 3])])
    t2 = Topology(2, [Shard(r(0, 10), [2, 3, 4])])
    ts = Topologies([t1, t2])
    assert ts.current_epoch == 2 and ts.oldest_epoch == 1
    assert ts.for_epoch(1) is t1 and ts.for_epoch(2) is t2
    assert ts.nodes() == {1, 2, 3, 4}
    assert ts.for_epochs(2, 2).size() == 1


def test_topology_manager_epochs_and_sync():
    tm = TopologyManager(node_id=1)
    t1 = Topology(1, [Shard(r(0, 10), [1, 2, 3])])
    t2 = Topology(2, [Shard(r(0, 10), [2, 3, 4])])
    tm.on_topology_update(t1)
    assert tm.current_epoch == 1
    assert tm.is_sync_complete(1)  # first epoch trivially synced
    tm.on_topology_update(t2)
    assert tm.current_epoch == 2
    assert not tm.is_sync_complete(2)
    # sync quorum for epoch 2's single shard {2,3,4} needs 2 acks
    tm.on_remote_sync_complete(2, 2)
    assert not tm.is_sync_complete(2)
    tm.on_remote_sync_complete(3, 2)
    assert tm.is_sync_complete(2)

    # open-epoch extension: coordination reaches back over epochs that are not
    # yet both synced AND closed — sync alone leaves in-flight old-epoch txns
    # invisible to deps rounds (exclusive sync points close epochs)
    t3 = Topology(3, [Shard(r(0, 10), [2, 3, 4])])
    tm.on_topology_update(t3)
    assert tm.with_unsynced_epochs(None, 3, 3).size() == 3  # 1,2 synced, NOT closed
    tm.on_epoch_closed(Ranges.of(r(0, 10)), 1)
    tm.on_epoch_closed(Ranges.of(r(0, 10)), 2)
    assert tm.with_unsynced_epochs(Ranges.of(r(0, 10)), 3, 3).size() == 1
    t4 = Topology(4, [Shard(r(0, 10), [2, 3, 4])])
    tm.on_topology_update(t4)
    # 3 neither synced nor closed -> include
    assert tm.with_unsynced_epochs(Ranges.of(r(0, 10)), 4, 4).size() == 2


def test_topology_manager_await_and_pending_sync():
    tm = TopologyManager(node_id=1)
    fut = tm.await_epoch(1)
    assert not fut.is_done()
    # sync report arriving before the topology is buffered
    tm.on_remote_sync_complete(2, 2)
    t1 = Topology(1, [Shard(r(0, 10), [1, 2, 3])])
    tm.on_topology_update(t1)
    assert fut.is_done()
    t2 = Topology(2, [Shard(r(0, 10), [1, 2, 3])])
    tm.on_topology_update(t2)
    tm.on_remote_sync_complete(3, 2)
    assert tm.is_sync_complete(2)


def test_topology_manager_truncate():
    tm = TopologyManager(node_id=1)
    for e in range(1, 5):
        tm.on_topology_update(Topology(e, [Shard(r(0, 10), [1, 2, 3])]))
    tm.truncate_until(3)
    assert tm.min_epoch == 3
    assert tm.has_epoch(3) and tm.has_epoch(4) and not tm.has_epoch(2)
