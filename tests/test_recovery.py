"""Recovery: completing or invalidating txns whose coordinator died mid-protocol.

Parity targets: accord.coordinate.Recover / messages.BeginRecovery behavior
(RecoverTest-style scenarios): recovery of a txn found PreAccepted-only is
invalidated (fast path provably not taken) or completed; recovery of an Accepted /
Committed / Applied txn completes it; ballot gates preempt stale coordinators.
"""
import pytest

from cassandra_accord_tpu.coordinate.errors import (CoordinationFailed, Exhausted,
                                                    Invalidated, Preempted, Timeout)
from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig
from cassandra_accord_tpu.impl.list_store import ListResult, list_txn
from cassandra_accord_tpu.local.status import SaveStatus, Status
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


class DropFrom(LinkConfig):
    """Drops messages sent from `dead` matching `predicate` once `active`."""

    def __init__(self, rng, dead_node: int):
        super().__init__(rng)
        self.dead = dead_node
        self.predicate = None

    def action(self, from_node: int, to_node: int, message=None) -> str:
        if self.predicate is not None and from_node == self.dead \
                and self.predicate(message):
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def make_cluster(seed=1, nodes=(1, 2, 3), dead=1):
    shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    topo = Topology(1, shards)
    from cassandra_accord_tpu.utils.random import RandomSource
    link = DropFrom(RandomSource(seed * 7 + 1), dead)
    cluster = Cluster(topo, seed=seed, link_config=link)
    return cluster, link


def start_and_kill_after(cluster, link, coordinator, kill_after_types, txn):
    """Coordinate from `coordinator`, dropping its outbound messages of the given
    types — simulating a coordinator that died after a phase."""
    link.predicate = lambda m: type(m).__name__ in kill_after_types
    res = cluster.nodes[coordinator].coordinate(txn)
    return res


def find_status(cluster, node_id, txn_id):
    for store in cluster.nodes[node_id].command_stores.all_stores():
        cmd = store.commands.get(txn_id)
        if cmd is not None:
            return cmd.save_status
    return None


def the_txn_id(cluster, node_id):
    """The single coordinated txn's id on the given node (None until witnessed)."""
    ids = set()
    for store in cluster.nodes[node_id].command_stores.all_stores():
        ids.update(store.commands.keys())
    return next(iter(ids)) if len(ids) == 1 else None


def test_recover_preaccepted_only_txn_invalidates():
    """Coordinator dies after PreAccept round: no Accept/Commit ever sent.  A
    recovering node must settle the txn (here: invalidate, since with all
    electorate members reporting preaccept-at-t0 but nothing proposed, the
    reference invalidates only if fast path impossible — otherwise completes at
    t0).  Either way every replica converges to a terminal state."""
    cluster, link = make_cluster()
    txn = list_txn([], {k(5): "a"})
    res = start_and_kill_after(cluster, link, 1, {"Commit", "Accept", "Apply"}, txn)
    # drive until the preaccept replies are in (coordinate() will stall at commit)
    cluster.run_until(lambda: the_txn_id(cluster, 2) is not None, max_tasks=10_000)
    txn_id = the_txn_id(cluster, 2)
    assert txn_id is not None

    link.predicate = None   # network heals; node 1 stays silent as coordinator
    rec = cluster.nodes[2].recover(txn_id, txn, txn.to_route())
    assert cluster.run_until(rec.is_done)
    cluster.run_until_idle()
    if rec.is_failure():
        assert isinstance(rec.failure, Invalidated)
        for n in (2, 3):
            assert find_status(cluster, n, txn_id) is SaveStatus.INVALIDATED
    else:
        # recovery completed the fast-path txn: value applied everywhere
        for n in (2, 3):
            assert cluster.stores[n].get(k(5)) == ("a",)


def test_recover_applied_txn_returns_result():
    """Recovery of an already-applied txn persists and reports its outcome."""
    cluster, link = make_cluster()
    txn = list_txn([], {k(5): "a"})
    res = cluster.nodes[1].coordinate(txn)
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    txn_id = the_txn_id(cluster, 2)

    rec = cluster.nodes[2].recover(txn_id, txn, txn.to_route())
    assert cluster.run_until(rec.is_done)
    assert rec.is_success(), rec.failure
    cluster.run_until_idle()
    for n in cluster.nodes:
        assert cluster.stores[n].get(k(5)) == ("a",)


def test_recover_stable_txn_completes_execution():
    """Coordinator dies after Stable is durable but before Apply: recovery must
    finish execution and apply the writes."""
    cluster, link = make_cluster()
    txn = list_txn([], {k(7): "x"})
    res = start_and_kill_after(cluster, link, 1, {"Apply"}, txn)
    # commit/stable reach replicas; the result may even resolve client-side
    def stable_somewhere():
        tid = the_txn_id(cluster, 2)
        if tid is None:
            return False
        return any(find_status(cluster, n, tid) is not None
                   and find_status(cluster, n, tid).has_been(Status.STABLE)
                   for n in (2, 3))
    cluster.run_until(stable_somewhere, max_tasks=50_000)
    txn_id = the_txn_id(cluster, 2)
    assert txn_id is not None

    link.predicate = None
    rec = cluster.nodes[2].recover(txn_id, txn, txn.to_route())
    assert cluster.run_until(rec.is_done)
    assert rec.is_success(), rec.failure
    cluster.run_until_idle()
    for n in (2, 3):
        assert cluster.stores[n].get(k(7)) == ("x",)


def test_recovered_txn_not_applied_twice():
    """Recovering an already-applied txn must not re-append the write."""
    cluster, link = make_cluster()
    txn = list_txn([], {k(9): "v"})
    res = cluster.nodes[1].coordinate(txn)
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    txn_id = the_txn_id(cluster, 2)

    for recoverer in (2, 3, 2):
        rec = cluster.nodes[recoverer].recover(txn_id, txn, txn.to_route())
        assert cluster.run_until(rec.is_done)
        cluster.run_until_idle()
    for n in cluster.nodes:
        assert cluster.stores[n].get(k(9)) == ("v",)


def test_second_recovery_preempts_first_ballot():
    """A later-ballot recovery preempts an earlier one (ballot gate on replicas)."""
    cluster, link = make_cluster()
    txn = list_txn([], {k(4): "z"})
    res = start_and_kill_after(cluster, link, 1, {"Commit", "Accept", "Apply"}, txn)
    cluster.run_until(lambda: the_txn_id(cluster, 2) is not None, max_tasks=10_000)
    txn_id = the_txn_id(cluster, 2)
    assert txn_id is not None
    link.predicate = None

    b_low = cluster.nodes[2].ballot_after(None)
    b_high = cluster.nodes[3].ballot_after(b_low)
    from cassandra_accord_tpu.coordinate.recover import recover as do_recover
    from cassandra_accord_tpu.utils import async_ as au
    # the higher ballot runs first and settles; the stale one must be rejected
    rec_high = au.settable()
    do_recover(cluster.nodes[3], txn_id, txn, txn.to_route(), rec_high, ballot=b_high)
    assert cluster.run_until(rec_high.is_done)
    cluster.run_until_idle()

    rec_low = au.settable()
    do_recover(cluster.nodes[2], txn_id, txn, txn.to_route(), rec_low, ballot=b_low)
    assert cluster.run_until(rec_low.is_done)
    # stale ballot is preempted — unless the txn already reached a terminal
    # decision, in which case reporting that decision is also correct
    if rec_low.is_failure():
        assert isinstance(rec_low.failure, (Preempted, Invalidated)), rec_low.failure


def test_recovery_converges_replicas_after_partial_apply():
    """Apply reached only node 2; recovery makes node 3 apply too."""
    class DropApplyTo3(LinkConfig):
        def action(self, from_node, to_node, message=None):
            if to_node == 3 and type(message).__name__ == "Apply":
                return LinkConfig.DROP
            return LinkConfig.DELIVER

    from cassandra_accord_tpu.utils.random import RandomSource
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=5,
                      link_config=DropApplyTo3(RandomSource(11)))
    txn = list_txn([], {k(6): "w"})
    res = cluster.nodes[1].coordinate(txn)
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    txn_id = the_txn_id(cluster, 2)
    assert cluster.stores[2].get(k(6)) == ("w",)
    assert cluster.stores[3].get(k(6)) == ()

    cluster.link = LinkConfig(RandomSource(12))  # heal
    rec = cluster.nodes[3].recover(txn_id, txn, txn.to_route())
    assert cluster.run_until(rec.is_done)
    assert rec.is_success(), rec.failure
    cluster.run_until_idle()
    assert cluster.stores[3].get(k(6)) == ("w",)


def test_await_commit_resolves_on_commit():
    """_AwaitCommit (WaitOnCommit quorum) resolves once the txn precommits."""
    from cassandra_accord_tpu.coordinate.recover import _AwaitCommit
    from cassandra_accord_tpu.primitives.deps import DepsBuilder

    cluster, link = make_cluster()
    # a txn held at preaccept (commit/apply dropped)
    txn = list_txn([], {k(8): "h"})
    start_and_kill_after(cluster, link, 1, {"Commit", "Accept", "Apply"}, txn)
    cluster.run_until(lambda: the_txn_id(cluster, 2) is not None, max_tasks=10_000)
    txn_id = the_txn_id(cluster, 2)

    deps = DepsBuilder().add(k(8).to_routing(), txn_id).build()
    waiter = _AwaitCommit(cluster.nodes[3], txn_id, deps.participants(txn_id))
    # heal the network and let recovery settle the txn -> waiter resolves
    # (WaitOnCommit replies only once the txn is decided on each replica)
    link.predicate = None
    rec = cluster.nodes[2].recover(txn_id, txn, txn.to_route())
    assert cluster.run_until(rec.is_done)
    assert cluster.run_until(waiter.result.is_done)
    assert waiter.result.is_success(), waiter.result.failure
