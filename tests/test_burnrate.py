"""Multi-window SLO burn-rate monitors (observe/burnrate.py).

The acceptance shape (ISSUE 10): on an injected journal-stall wedge the
``slo.burn`` monitor flags the degradation strictly earlier (sim time) than
the watchdog's stall exit, and it stays silent across the clean matrix.
Plus the monitor math itself: two-window confirmation (a short burst alone
cannot fire), minimum bad-event count, episode clear.
"""
import re

import pytest

from cassandra_accord_tpu.harness import burn as burn_mod
from cassandra_accord_tpu.harness.burn import SimulationException, run_burn
from cassandra_accord_tpu.harness.watchdog import StallError
from cassandra_accord_tpu.observe import (BurnRateMonitor, FlightRecorder,
                                          InvariantAuditor, SloSpec, Timeline)


# ---------------------------------------------------------------------------
# monitor math (synthetic event streams, no burn)
# ---------------------------------------------------------------------------

def _latency_spec(**kw):
    defaults = dict(budget=0.1, short_s=1.0, long_s=10.0, burn_threshold=5.0,
                    min_bad=2, latency_slo_us=100)
    defaults.update(kw)
    return SloSpec("t", "latency", **defaults)


def test_short_burst_alone_does_not_fire():
    """The two-window guard: a healthy long window vetoes a short bad
    burst (the standard multi-window burn-rate construction)."""
    m = BurnRateMonitor(specs=(_latency_spec(),))
    for i in range(100):                      # 10 good/s for 10 sim-seconds
        m.on_resolution("fast", 50, now_us=i * 100_000)
    for i in range(4):                        # short bad burst at t=10s
        m.on_resolution("fast", 500, now_us=10_000_000 + i * 1_000)
    assert m.events == [], "short burst fired without long-window confirmation"


def test_sustained_burn_fires_and_clears():
    m = BurnRateMonitor(specs=(_latency_spec(),))
    for i in range(100):
        m.on_resolution("fast", 50, now_us=i * 100_000)
    t = 10_000_000
    while t < 21_000_000:                     # sustained bad for 11 sim-s
        m.on_resolution("fast", 500, now_us=t)
        t += 200_000
    assert len(m.events) == 1
    ev = m.events[0]
    assert ev["kind"] == "slo.burn" and ev["slo"] == "t"
    assert ev["short_burn_rate"] >= 5.0 and ev["long_burn_rate"] >= 5.0
    assert ev["cleared_us"] is None and m.open_burns()
    while t < 45_000_000:                     # recovery: good events again
        m.on_resolution("fast", 50, now_us=t)
        t += 200_000
    assert ev["cleared_us"] is not None and m.open_burns() == []
    assert len(m.events) == 1, "recovery must clear, not re-fire"


def test_min_bad_events_guard():
    """Below min_bad the monitor cannot fire even at infinite burn rate
    (one unlucky txn in an otherwise-quiet window)."""
    m = BurnRateMonitor(specs=(_latency_spec(min_bad=5),))
    for i in range(3):
        m.on_resolution("fast", 500, now_us=20_000_000 + i * 100_000)
    assert m.events == []


def test_failed_outcome_counts_bad_and_flags_drive_liveness():
    lat = _latency_spec()
    live = SloSpec("live", "liveness", budget=0.1, short_s=1.0, long_s=10.0,
                   burn_threshold=5.0, min_bad=2)
    m = BurnRateMonitor(specs=(lat, live))
    for i in range(30):
        m.on_flag_opened("slo.unattended", now_us=20_000_000 + i * 100_000)
    fired = {e["slo"] for e in m.events}
    assert "live" in fired and "t" not in fired
    m2 = BurnRateMonitor(specs=(_latency_spec(),))
    for i in range(30):                       # failed ops burn latency SLO
        m2.on_resolution("failed", None, now_us=20_000_000 + i * 100_000)
    assert {e["slo"] for e in m2.events} == {"t"}


# ---------------------------------------------------------------------------
# the clean matrix stays silent
# ---------------------------------------------------------------------------

def test_silent_on_clean_matrix():
    """A benign burn (no faults) with monitors + auditor attached: zero
    slo.burn events, zero registry burn counters."""
    monitor = BurnRateMonitor()
    auditor = InvariantAuditor(mode="strict", burnrate=monitor)
    run_burn(4, ops=120, concurrency=12, journal=True, durability=True,
             observer=auditor, audit="strict")
    assert monitor.events == []
    assert monitor.report()["slo_burn_events"] == 0
    snap = auditor.metrics_snapshot().get("cluster", {})
    assert not any(k.startswith("slo.burn") for k in snap)


# ---------------------------------------------------------------------------
# the acceptance shape: early warning on an injected journal-stall wedge
# ---------------------------------------------------------------------------

def test_burn_monitor_fires_before_watchdog_on_injected_stall():
    """Inject a total journal-stall wedge mid-burn (every node's append path
    stalls; fsync-before-reply holds all outbound packets).  The watchdog
    exits at wedge + 30 sim-seconds; the slo.burn monitor must flag the
    wedge STRICTLY earlier, and the stall dump must embed the burn events
    and the last-N timeline windows (the trajectory into the stall)."""
    monitor = BurnRateMonitor()
    auditor = InvariantAuditor(mode="warn", slo_unattended_s=2.0,
                               burnrate=monitor, timeline=Timeline())
    wedged = {"at_us": None}

    def wedge(op_id, txn_id, txn, coordinator):
        if op_id == 30 and wedged["at_us"] is None:
            cluster = burn_mod.last_cluster()
            wedged["at_us"] = cluster.now_micros
            for n in sorted(cluster.nodes):
                cluster.stall_journal(n)

    with pytest.raises(SimulationException) as ei:
        run_burn(2, ops=400, concurrency=10, journal=True, durability=True,
                 observer=auditor, audit="warn", on_submit=wedge,
                 stall_watchdog_s=60.0, max_tasks=20_000_000)
    cause = ei.value.cause
    assert isinstance(cause, StallError), f"expected a stall, got {cause!r}"
    assert wedged["at_us"] is not None, "the wedge never injected"
    # the monitor fired, and strictly earlier than the watchdog's exit
    assert monitor.events, "no slo.burn event on a total wedge"
    first_burn_us = monitor.events[0]["sim_us"]
    m = re.search(r"sim_time_s=([0-9.]+)", cause.dump)
    assert m, "stall dump lost its sim_time_s header"
    stall_us = float(m.group(1)) * 1e6
    assert wedged["at_us"] < first_burn_us < stall_us, \
        f"monitor fired at {first_burn_us}us, watchdog at {stall_us}us " \
        f"(wedge at {wedged['at_us']}us) — not an early warning"
    # the warn-stream verdict carries the burn events (failure path too)
    verdict = ei.value.audit
    assert verdict is not None and verdict["slo_burn_events"] >= 1
    assert verdict["first_slo_burn"]["sim_us"] == first_burn_us
    # the stall dump embeds both trajectory sections
    assert "slo_burn: " in cause.dump
    assert "timeline: " in cause.dump


def test_cli_burnrate_implies_audit_warn(tmp_path, capsys):
    """``--burnrate`` with auditing off upgrades to ``--audit=warn``: the
    liveness monitors burn on the auditor's flag plane and the report rides
    the audit verdict — without the upgrade a total wedge would starve both
    monitor streams and the flag would silently do nothing."""
    out = tmp_path / "b.json"
    burn_mod.main(["--seeds", "0", "--ops", "25", "--benign", "--no-churn",
                   "--burnrate", "--json", str(out)])
    assert "--burnrate implies --audit=warn" in capsys.readouterr().out
    import json
    entry = json.loads(out.read_text())["results"][0]
    assert entry["status"] == "pass"
    # the audit verdict exists (warn plane) and carries the monitor report
    assert entry["audit"]["mode"] == "warn"
    assert entry["audit"]["slo_burn_events"] == 0


def test_perfetto_commits_track_drops_to_zero_through_a_wedge():
    """The Perfetto counter track emits commits_per_sec=0.0 for windows
    with message traffic but no commit outcomes — Perfetto holds a counter
    at its last sample, so skipping those windows would render a stall as
    a flat healthy line."""
    from cassandra_accord_tpu.observe.export import timeline_counter_events
    from cassandra_accord_tpu.observe import schema
    tl = Timeline(window_us=1_000_000)
    rec = FlightRecorder(timeline=tl)
    # window 0: one commit; windows 1-2: probes/timeouts only (the wedge)
    tl.count(schema.OUTCOME_METRICS["fast"], 100)
    tl.count("net.reply_timeouts", 1_000_100)
    tl.count("net.reply_timeouts", 2_000_100)
    events = timeline_counter_events(rec)
    cps = [e["args"]["commits_per_sec"] for e in events]
    assert cps == [1.0, 0.0, 0.0]


def test_stall_dump_timeline_shows_commits_drying_up():
    """The embedded windows are the trajectory INTO the stall: early windows
    carry resolutions, the tail windows carry none (that is what the
    watchdog reader needs to see at a glance)."""
    monitor = BurnRateMonitor()
    timeline = Timeline()
    rec = FlightRecorder(timeline=timeline, burnrate=monitor)
    wedged = {"done": False}

    def wedge(op_id, txn_id, txn, coordinator):
        if op_id == 25 and not wedged["done"]:
            wedged["done"] = True
            cluster = burn_mod.last_cluster()
            for n in sorted(cluster.nodes):
                cluster.stall_journal(n)

    with pytest.raises(SimulationException) as ei:
        run_burn(2, ops=400, concurrency=10, journal=True, durability=True,
                 observer=rec, on_submit=wedge,
                 stall_watchdog_s=20.0, max_tasks=20_000_000)
    assert isinstance(ei.value.cause, StallError)
    from cassandra_accord_tpu.observe.timeline import commits_per_sec_series
    series = commits_per_sec_series(timeline.records())
    assert series, "no commits/s windows recorded"
    windows = {w for w, _v in series}
    last_window = max(r["window"] for r in timeline.records())
    # the tail of the run (the stalled stretch) has NO commit windows
    assert last_window - 5 > max(windows), \
        "commit windows continue into the stall — wedge not visible"
