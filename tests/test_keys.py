"""Keys/Ranges sorted-set algebra.

Parity targets: AbstractKeys/AbstractRanges/Range semantics
(AbstractRanges.java:1-788, Range.java:1-451) exercised property-style against
set-based oracles.
"""
from cassandra_accord_tpu.primitives.keys import (
    IntKey, Keys, Range, Ranges, RoutingKeys, SentinelKey,
)
from cassandra_accord_tpu.utils.random import RandomSource


def k(v, p=0):
    return IntKey(v, p)


def r(a, b, p=0):
    return Range(k(a, p), k(b, p))


def test_keys_basic():
    ks = Keys.of([k(3), k(1), k(2), k(1)])
    assert len(ks) == 3
    assert [key.value for key in ks] == [1, 2, 3]
    assert ks.contains(k(2)) and not ks.contains(k(4))
    assert ks.index_of(k(2)) == 1
    assert ks.index_of(k(4)) == -4  # insertion point 3 -> -3-1


def test_keys_union_intersect():
    a = Keys.of([k(1), k(3), k(5)])
    b = Keys.of([k(2), k(3), k(6)])
    assert [x.value for x in a.union(b)] == [1, 2, 3, 5, 6]
    assert a.intersects(b)
    assert not Keys.of([k(1)]).intersects(Keys.of([k(2)]))


def test_keys_slice_by_ranges():
    ks = Keys.of([k(i) for i in range(10)])
    sliced = ks.slice(Ranges.of(r(2, 5), r(7, 9)))
    assert [x.value for x in sliced] == [2, 3, 4, 7, 8]  # half-open


def test_range_ops():
    a, b = r(0, 10), r(5, 15)
    assert a.intersects(b)
    assert a.intersection(b) == r(5, 10)
    assert not r(0, 5).intersects(r(5, 10))  # half-open adjacency
    assert a.contains(k(0)) and a.contains(k(9)) and not a.contains(k(10))
    assert r(0, 20).contains_range(b)


def test_ranges_normalize_coalesce():
    rs = Ranges.of(r(5, 10), r(0, 6), r(12, 15))
    assert list(rs) == [r(0, 10), r(12, 15)]
    assert rs.contains(k(9)) and not rs.contains(k(11))


def test_ranges_algebra():
    a = Ranges.of(r(0, 10), r(20, 30))
    b = Ranges.of(r(5, 25))
    assert list(a.intersection(b)) == [r(5, 10), r(20, 25)]
    assert list(a.union(b)) == [r(0, 30)]
    assert list(a.without(b)) == [r(0, 5), r(25, 30)]
    assert a.intersects(b)
    assert a.contains_all(Ranges.of(r(2, 8)))
    assert not a.contains_all(Ranges.of(r(8, 12)))


def test_prefix_sentinels():
    full0 = Range.full_prefix(0)
    full1 = Range.full_prefix(1)
    assert full0.contains(k(999999, 0)) and not full0.contains(k(0, 1))
    assert not full0.intersects(full1)
    assert SentinelKey.min(0) < k(-10**9, 0) < k(10**9, 0) < SentinelKey.max(0) < SentinelKey.min(1)


def test_random_against_set_oracle():
    rng = RandomSource(7)
    for _ in range(50):
        xs = {rng.next_int(100) for _ in range(rng.next_int(1, 30))}
        ys = {rng.next_int(100) for _ in range(rng.next_int(1, 30))}
        a, b = Keys.of(map(k, xs)), Keys.of(map(k, ys))
        assert {x.value for x in a.union(b)} == xs | ys
        lo = rng.next_int(0, 50)
        hi = rng.next_int(lo + 1, 101)
        sliced = a.slice(Ranges.of(r(lo, hi)))
        assert {x.value for x in sliced} == {v for v in xs if lo <= v < hi}
        assert a.intersects(b) == bool(xs & ys)
