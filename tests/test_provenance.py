"""Causal provenance tracing & divergence forensics (observe/provenance.py).

Three hard contracts:

1. ZERO OBSERVER EFFECT: a same-seed hostile burn with the provenance
   recorder ON vs OFF yields byte-identical full message traces
   (``diff_traces`` is None) and identical outcome counters — the PR-3
   proof, extended to the causal side table.
2. MUTATION LOCALIZATION: a single seeded perturbation (an injected crash,
   a delayed timer-shaped fault-in) between two otherwise-identical runs is
   named by ``explain_divergence`` as the causally-FIRST divergent event —
   not merely the first differing message byte, which lands later — and the
   injected event is inside the report's ancestor cone.
3. VIOLATION SLICING: every strict-mode ``AuditViolation`` raised with a
   provenance recorder attached carries a bounded backward causal slice
   whose anchor is the transition that tripped the rule.
"""
import json

import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.observe import (AuditViolation, FlightRecorder,
                                          InvariantAuditor,
                                          ProvenanceRecorder,
                                          explain_divergence, render_slice,
                                          validate_chrome_trace)
from cassandra_accord_tpu.observe import rules
from cassandra_accord_tpu.observe.provenance import (E_KIND, E_P1, E_P2,
                                                     E_PID, E_US, K_CRASH,
                                                     K_HANDLER, K_MSG,
                                                     K_TIMER, K_TRANSITION)
from cassandra_accord_tpu.primitives.timestamp import (Domain, TxnId,
                                                       TxnKind)

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)

# the mutation regime: no chaos nemesis, so every node is guaranteed live
# at the injection time and the ONLY difference between run a and run b is
# the perturbation itself
QUIET = dict(ops=80, concurrency=8, chaos=False, allow_failures=True,
             durability=True, journal=True, max_tasks=3_000_000)


def tid(hlc: int, node: int = 1) -> TxnId:
    return TxnId(epoch=1, hlc=hlc, node=node, kind=TxnKind.WRITE,
                 domain=Domain.KEY)


# ---------------------------------------------------------------------------
# the tentpole invariant: zero observer effect
# ---------------------------------------------------------------------------

def test_zero_observer_effect_hostile():
    """Same-seed hostile burn with provenance ON vs OFF: identical full
    message traces and identical outcomes — recording the causal DAG never
    perturbs the simulation."""
    ta, tb = Trace(), Trace()
    bare = run_burn(9, tracer=ta.hook, **HOSTILE)
    prov = ProvenanceRecorder()
    observed = run_burn(9, tracer=tb.hook, provenance=prov, **HOSTILE)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"provenance recorder perturbed the simulation:\n{divergence}"
    assert (bare.ops_ok, bare.ops_recovered, bare.ops_nacked, bare.ops_lost,
            bare.ops_failed, bare.sim_micros) == \
           (observed.ops_ok, observed.ops_recovered, observed.ops_nacked,
            observed.ops_lost, observed.ops_failed, observed.sim_micros)
    # the side table is keyed by trace seq: one entry per traced message
    # event, each pointing at a msg-kind DAG node
    assert len(prov.seq_to_pid) == len(tb.events)
    assert all(prov.events[p][E_KIND] == K_MSG for p in prov.seq_to_pid)
    # the DAG is a strict superset of the message plane: handler executions
    # and save-status transitions are first-class events
    kinds = {ev[E_KIND] for ev in prov.events}
    assert {K_MSG, K_HANDLER, K_TRANSITION, K_TIMER} <= kinds
    # parent edges are well-formed: strictly backward, in range
    for ev in prov.events:
        for parent in (ev[E_P1], ev[E_P2]):
            if parent is not None:
                assert 0 <= parent < ev[E_PID]


def test_provenance_on_vs_off_same_causal_dag(tmp_path):
    """Two same-seed runs with provenance on both sides build the SAME DAG
    (content-wise), and save/load round-trips it."""
    pa, pb = ProvenanceRecorder(), ProvenanceRecorder()
    run_burn(11, provenance=pa, **HOSTILE)
    run_burn(11, provenance=pb, **HOSTILE)
    assert explain_divergence(pa, pb) is None
    path = tmp_path / "prov.json"
    pa.save(str(path))
    doc = ProvenanceRecorder.load(str(path))
    assert doc["version"] == 1 and len(doc["events"]) == len(pa.events)
    # a loaded doc aligns against a live recorder
    assert explain_divergence(doc, pb) is None
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"version": 99}))
        ProvenanceRecorder.load(str(bad))


# ---------------------------------------------------------------------------
# mutation checks: the explainer localizes an injected perturbation
# ---------------------------------------------------------------------------

def test_explain_localizes_injected_crash():
    """Run b = run a + one crash injected at sim 2s (restart at 5s keeps the
    burn live).  The crash emits NO message-trace byte at injection time, so
    a byte-level diff can only see downstream symptoms — the causal
    explainer must name the crash itself as the first divergent event."""
    crash_us = 2_000_000
    pa, pb = ProvenanceRecorder(), ProvenanceRecorder()
    run_burn(7, provenance=pa, **QUIET)

    def perturb(cluster):
        cluster.queue.add_after(crash_us, lambda: cluster.crash(2))
        cluster.queue.add_after(5_000_000, lambda: cluster.restart(2))

    run_burn(7, provenance=pb, perturb=perturb, **QUIET)
    rep = explain_divergence(pa, pb)
    assert rep is not None, "injected crash produced no divergence"
    # the causally-first divergent event IS the injection, at its exact
    # injection time
    assert rep["event_b"]["kind"] == K_CRASH
    assert rep["event_b"]["sim_us"] == crash_us
    assert "crash node2" in rep["event_b"]["what"]
    # the ancestor cone reaches the injection point
    assert any(d["kind"] == K_CRASH and d["sim_us"] == crash_us
               for d in rep["cone"])
    # the byte-level symptom is NOT the explanation: the first differing
    # message event (if the traces differ at all) is a downstream
    # consequence at-or-after the injection, and is never a crash
    msg = rep["first_message_divergence"]
    if msg is not None:
        for side in ("event_a", "event_b"):
            if side in msg:
                assert msg[side]["sim_us"] >= crash_us
                assert msg[side]["kind"] == K_MSG
    assert "causal divergence" in rep["text"]


def test_explain_localizes_delayed_work():
    """Run b = run a + one no-op-shaped scheduling perturbation that fires a
    visible fault-in later (crash+restart at 6s): every event BEFORE the
    injection stays shared, pinning the alignment prefix."""
    pa, pb = ProvenanceRecorder(), ProvenanceRecorder()
    run_burn(8, provenance=pa, **QUIET)

    def perturb(cluster):
        cluster.queue.add_after(6_000_000, lambda: cluster.crash(3))
        cluster.queue.add_after(8_000_000, lambda: cluster.restart(3))

    run_burn(8, provenance=pb, perturb=perturb, **QUIET)
    rep = explain_divergence(pa, pb)
    assert rep is not None
    assert rep["event_b"]["kind"] == K_CRASH
    assert rep["event_b"]["sim_us"] == 6_000_000
    # everything in the cone before the divergence index is marked shared —
    # the causal run-up both runs agreed on
    for d in rep["cone"]:
        if d["pid"] < rep["index"]:
            assert d["shared"]


# ---------------------------------------------------------------------------
# violation slicing
# ---------------------------------------------------------------------------

def test_strict_violation_carries_causal_slice():
    prov = ProvenanceRecorder()
    auditor = InvariantAuditor(mode="strict", provenance=prov)
    t = tid(100)
    auditor.on_transition(1, 0, t, "STABLE", 10)
    auditor.on_transition(1, 0, t, "READY_TO_EXECUTE", 20)
    with pytest.raises(AuditViolation) as exc:
        auditor.on_transition(1, 0, t, "PRE_ACCEPTED", 30)
    v = exc.value
    assert v.rule == rules.RULE_ILLEGAL_EDGE
    sl = v.causal_slice
    assert sl is not None
    # the anchor is the transition that tripped the rule (recorded BEFORE
    # the rule check ran), and the report embeds the slice
    anchor = [d for d in sl["events"] if d["pid"] == sl["anchor_pid"]]
    assert len(anchor) == 1
    assert anchor[0]["kind"] == K_TRANSITION
    assert "PRE_ACCEPTED" in anchor[0]["what"]
    assert v.report()["causal_slice"] == sl
    rendered = render_slice(sl)
    assert "causal slice" in rendered and "PRE_ACCEPTED" in rendered
    # without provenance the slice is absent, not empty
    bare = InvariantAuditor(mode="warn")
    bare.on_transition(1, 0, t, "APPLIED", 10)
    bare.on_transition(1, 0, t, "PRE_ACCEPTED", 20)
    assert bare.violations[0].causal_slice is None
    assert "causal_slice" not in bare.violations[0].report()


def test_slice_for_anchors_and_fallbacks():
    prov = ProvenanceRecorder()
    t = tid(7)
    prov.on_message_event("SEND", 1, 2, 5, None, 100)
    prov.on_transition(2, 0, t, "PRE_ACCEPTED", 200)
    prov.on_transition(2, 0, t, "STABLE", 300)
    prov.on_transition(3, 0, t, "PRE_ACCEPTED", 400)
    # exact (node, store) anchor: the txn's LATEST transition there
    sl = prov.slice_for(txn_id=t, node=2, store=0)
    assert prov.events[sl["anchor_pid"]][E_US] == 300
    # unknown store falls back to the latest transition anywhere
    sl2 = prov.slice_for(txn_id=t, node=9, store=9)
    assert prov.events[sl2["anchor_pid"]][E_US] == 400
    # no txn at all: the latest event of any kind
    sl3 = prov.slice_for()
    assert sl3["anchor_pid"] == len(prov.events) - 1
    # unknown txn: no anchor, no slice
    assert prov.slice_for(txn_id=tid(999)) is None
    # empty recorder
    assert ProvenanceRecorder().slice_for() is None


def test_ancestor_cone_bounded_and_chained():
    """A RECV claimed by an immediately-following handler chains handler ->
    delivery -> send; an interleaved event breaks the claim."""
    prov = ProvenanceRecorder()
    prov.on_message_event("SEND", 1, 2, 5, None, 100)
    prov.on_message_event("RECV", 1, 2, 5, None, 150)
    prov.begin_handler(2, "PreAccept", tid(1), 150)
    prov.on_transition(2, 0, tid(1), "PRE_ACCEPTED", 150)
    prov.end()
    send, recv, handler, transition = prov.events
    assert handler[E_P2] == recv[E_PID]       # handler <- its delivery
    assert recv[E_P2] == send[E_PID]          # delivery <- its send
    assert transition[E_P1] == handler[E_PID]  # transition <- its handler
    assert prov.ancestors(transition[E_PID]) == [0, 1, 2, 3]
    assert prov.ancestors(transition[E_PID], hops=1) == [2, 3]
    # an interleaved event clears the pending-recv claim
    prov.on_message_event("RECV", 2, 3, 6, None, 200)
    prov.on_message_event("DROP", 2, 4, 7, None, 210)
    prov.begin_handler(3, "Accept", None, 220)
    assert prov.events[-1][E_P2] is None
    prov.end()


def test_history_checker_attaches_causal_slices():
    """check_history(provenance=...) decorates anomaly reports: each
    implicated op with a known txn gains a causal slice (and the text
    report says so)."""
    from cassandra_accord_tpu.observe.checker import (HistoryAnomaly,
                                                      check_history,
                                                      format_report)
    from cassandra_accord_tpu.observe.history import HistoryRecorder
    prov = ProvenanceRecorder()
    prov.on_transition(1, 0, "t1", "APPLIED", 100)
    # lost update: an acked write whose value never made the final order
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"k": "a"})
    rec.resolve(1, "ok", 100, writes={"k": "a"})
    with pytest.raises(HistoryAnomaly) as exc:
        check_history(rec.ops, final_state={"k": ("b",)}, provenance=prov)
    report = exc.value.report
    a = report["anomalies"][0]
    assert a["name"] == "lost-update"
    assert "t1" in a["causal_slices"]
    sl = a["causal_slices"]["t1"]
    assert any("APPLIED" in d["what"] for d in sl["events"])
    assert "causal slices attached" in format_report(report)


# ---------------------------------------------------------------------------
# exports: causal flow arrows + watchdog dump section
# ---------------------------------------------------------------------------

def test_chrome_trace_causal_flows_valid():
    """--provenance + --trace-out: causal flow arrows ride the Perfetto
    export and the artifact stays schema-valid (every flow id has a start,
    every finish pairs with one)."""
    prov = ProvenanceRecorder()
    rec = FlightRecorder(record_messages=True, provenance=prov)
    run_burn(13, observer=rec, **HOSTILE)
    doc = rec.chrome_trace()
    assert validate_chrome_trace(doc) == []
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "causal"]
    assert flows, "no causal flow events exported"
    by_id: dict = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e["ph"])
    for fid, phases in by_id.items():
        assert phases[0] == "s" and phases[-1] == "f", fid
    finishes = [e for e in flows if e["ph"] == "f"]
    assert all(e.get("bp") == "e" for e in finishes)


def test_validator_rejects_unmatched_flow_finish():
    """Satellite: the validator must flag an ``f`` with no matching ``s``
    (it previously only checked starts/ids)."""
    base = {"cat": "causal", "ts": 1, "pid": 0, "tid": 0, "name": "x"}
    s = dict(base, ph="s", id="flow-1")
    f = dict(base, ph="f", id="flow-1", bp="e")
    orphan = dict(base, ph="f", id="flow-2", bp="e")
    assert validate_chrome_trace({"traceEvents": [s, f]}) == []
    problems = validate_chrome_trace({"traceEvents": [s, f, orphan]})
    assert any("no matching start" in p for p in problems), problems


def test_watchdog_dump_includes_provenance_section():
    from cassandra_accord_tpu.harness.burn import last_cluster
    from cassandra_accord_tpu.harness.watchdog import dump_wait_state
    prov = ProvenanceRecorder()
    rec = FlightRecorder(provenance=prov)
    run_burn(11, ops=10, concurrency=4, observer=rec)
    cluster = last_cluster()
    assert cluster is not None
    dump = dump_wait_state(cluster)
    assert "provenance: " in dump
    line = next(l for l in dump.splitlines()
                if l.startswith("provenance: "))
    doc = json.loads(line.split("provenance: ", 1)[1])
    assert doc["tail"]["events_total"] == len(prov.events)
    assert "stall_root_slices" in doc
