"""Device deps-kernel tests: every kernel checked against a naive NumPy oracle.

The oracle implements the reference semantics directly (per-txn loops over
CommandsForKey-style conflict scans); the kernels must match bit-exactly —
this is the "deps-graph parity" requirement from BASELINE.md.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from cassandra_accord_tpu import ops
from cassandra_accord_tpu.ops import graph_state as gs
from cassandra_accord_tpu.ops.pallas_join import overlap_join_fused
from cassandra_accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind, Domain

T, K, B = 64, 32, 16


def _mk_txns(rng: np.random.Generator, n: int):
    """n random txns touching 1-4 of K keys: (key_inc, lanes, kinds, txn_ids)."""
    key_inc = np.zeros((n, K), dtype=np.int8)
    kinds = np.zeros(n, dtype=np.int8)
    lanes = np.zeros((n, gs.TS_LANES), dtype=np.int32)
    txn_ids = []
    for i in range(n):
        nkeys = rng.integers(1, 5)
        key_inc[i, rng.choice(K, nkeys, replace=False)] = 1
        kind = TxnKind(rng.choice([0, 1, 3, 4]))
        tid = TxnId(epoch=1, hlc=int(rng.integers(1, 500)),
                    node=int(rng.integers(1, 8)), kind=kind, domain=Domain.KEY)
        txn_ids.append(tid)
        kinds[i] = int(kind)
        lanes[i] = tid.pack_lanes()
    return key_inc, lanes, kinds, txn_ids


def _mk_index(rng: np.random.Generator):
    key_inc, lanes, kinds, txn_ids = _mk_txns(rng, T)
    statuses = rng.integers(gs.PREACCEPTED, gs.INVALIDATED + 1, T).astype(np.int8)
    active = rng.random(T) < 0.9
    return key_inc, lanes, kinds, statuses, active, txn_ids


def _oracle_join(ikey, itid, ikind, istat, iact, bkey, btid, bkind):
    """Reference semantics, txn by txn (cfk mapReduceActive loop)."""
    out = np.zeros((len(bkey), len(ikey)), dtype=bool)
    for bi in range(len(bkey)):
        for ti in range(len(ikey)):
            if not iact[ti] or istat[ti] == gs.INVALIDATED:
                continue
            if not (bkey[bi] & ikey[ti]).any():
                continue
            if not TxnKind(bkind[bi]).witnesses(TxnKind(ikind[ti])):
                continue
            if tuple(itid[ti]) < tuple(btid[bi]):
                out[bi, ti] = True
    return out


@pytest.fixture
def nprng():
    return np.random.default_rng(7)


def test_pack_lanes_roundtrip_and_order():
    a = Timestamp(epoch=3, hlc=(1 << 50) + 12345, node=9, flags=0x8000)
    b = Timestamp(epoch=3, hlc=(1 << 50) + 12346, node=1)
    assert Timestamp.unpack_lanes(a.pack_lanes()) == a
    assert (a < b) == (tuple(a.pack_lanes()) < tuple(b.pack_lanes()))
    # wall-clock-microsecond HLC (the production clock) stays in bounds
    wall = Timestamp(epoch=10, hlc=1_785_320_667_412_592, node=3)
    assert all(0 <= x <= bound for x, bound
               in zip(wall.pack_lanes(), Timestamp.LANE_BOUNDS))


def test_overlap_join_parity(nprng):
    ikey, itid, ikind, istat, iact, _ = _mk_index(nprng)
    bkey, btid, bkind, _ = _mk_txns(nprng, B)
    got = np.asarray(ops.overlap_join(
        jnp.asarray(ikey), jnp.asarray(itid), jnp.asarray(ikind),
        jnp.asarray(istat), jnp.asarray(iact),
        jnp.asarray(bkey), jnp.asarray(btid), jnp.asarray(bkind)))
    want = _oracle_join(ikey, itid, ikind, istat, iact, bkey, btid, bkind)
    assert (got == want).all()


def test_pallas_join_matches_xla(nprng):
    ikey, itid, ikind, istat, iact, _ = _mk_index(nprng)
    bkey, btid, bkind, _ = _mk_txns(nprng, B)
    xla = np.asarray(ops.overlap_join(
        jnp.asarray(ikey), jnp.asarray(itid), jnp.asarray(ikind),
        jnp.asarray(istat), jnp.asarray(iact),
        jnp.asarray(bkey), jnp.asarray(btid), jnp.asarray(bkind)))
    fused = np.asarray(overlap_join_fused(
        jnp.asarray(ikey), jnp.asarray(itid), jnp.asarray(ikind),
        jnp.asarray(istat), jnp.asarray(iact),
        jnp.asarray(bkey), jnp.asarray(btid), jnp.asarray(bkind)))
    assert (xla == fused).all()


def test_max_conflict_ts_matches_host_proposal(nprng):
    """Device conflict-max + host unique_now_at_least == host preaccept
    proposal (local/commands.py preaccept timestamp rule)."""
    ikey, itid, ikind, istat, iact, itxns = _mk_index(nprng)
    bkey, btid, bkind, btxns = _mk_txns(nprng, B)
    deps = _oracle_join(ikey, itid, ikind, istat, iact, bkey, btid, bkind)
    cmax, any_dep = ops.max_conflict_ts(jnp.asarray(itid), jnp.asarray(deps))
    cmax, any_dep = np.asarray(cmax), np.asarray(any_dep)
    for bi in range(B):
        conf = [tuple(itid[ti]) for ti in range(len(itid)) if deps[bi, ti]]
        assert bool(any_dep[bi]) == bool(conf)
        if conf:
            assert tuple(cmax[bi]) == max(conf)
            # host proposal rule: txnId wins iff maxConflict < txnId
            max_conflict = Timestamp.unpack_lanes(cmax[bi])
            fast = max_conflict < btxns[bi]
            assert fast == (tuple(cmax[bi]) < tuple(btid[bi]))
        else:
            assert tuple(cmax[bi]) == (0,) * gs.TS_LANES


def _random_dag(nprng, n=T, p=0.08):
    adj = (nprng.random((n, n)) < p)
    adj = np.tril(adj, k=-1)  # i depends on j<i: acyclic
    return adj.astype(np.int8)


def test_transitive_closure(nprng):
    adj = _random_dag(nprng)
    got = np.asarray(ops.transitive_closure(jnp.asarray(adj)))
    want = adj.astype(bool)
    for k in range(T):
        want = want | (want[:, k:k + 1] & want[k:k + 1, :])
    assert (got == want).all()


def test_elide_preserves_reachability(nprng):
    adj = _random_dag(nprng, p=0.15)
    reduced = np.asarray(ops.elide(jnp.asarray(adj)))
    assert (reduced <= adj.astype(bool)).all()
    full = np.asarray(ops.transitive_closure(jnp.asarray(adj)))
    again = np.asarray(ops.transitive_closure(jnp.asarray(reduced.astype(np.int8))))
    assert (full == again).all()
    # and it is minimal on DAGs: removing any kept edge loses reachability
    kept = np.argwhere(reduced)
    for (i, j) in kept[:10]:
        trial = reduced.copy()
        trial[i, j] = False
        r = np.asarray(ops.transitive_closure(jnp.asarray(trial.astype(np.int8))))
        assert not r[i, j]


def test_kahn_frontier(nprng):
    adj = _random_dag(nprng)
    status = np.full(T, gs.STABLE, dtype=np.int8)
    done = nprng.random(T) < 0.3
    status[done] = gs.APPLIED
    active = np.ones(T, dtype=bool)
    got = np.asarray(ops.kahn_frontier(
        jnp.asarray(adj), jnp.asarray(status), jnp.asarray(active)))
    for i in range(T):
        deps_done = all(status[j] in (gs.APPLIED, gs.INVALIDATED) or not active[j]
                        for j in range(T) if adj[i, j])
        want = active[i] and status[i] == gs.STABLE and deps_done
        assert got[i] == want, i


def test_kahn_levels_respects_edges(nprng):
    adj = _random_dag(nprng)
    active = nprng.random(T) < 0.95
    level = np.asarray(ops.kahn_levels(jnp.asarray(adj), jnp.asarray(active)))
    for i in range(T):
        if not active[i]:
            assert level[i] == -1
            continue
        assert level[i] >= 0
        for j in range(T):
            if adj[i, j] and active[j]:
                assert level[i] > level[j]


def test_kahn_levels_cycle_flagged():
    adj = np.zeros((8, 8), dtype=np.int8)
    adj[0, 1] = adj[1, 2] = adj[2, 0] = 1   # 3-cycle
    adj[3, 0] = 1                            # depends on the cycle
    active = np.ones(8, dtype=bool)
    active[5:] = False
    level = np.asarray(ops.kahn_levels(jnp.asarray(adj), jnp.asarray(active)))
    assert (level[[0, 1, 2, 3]] == -1).all()
    assert level[4] == 0
    assert (level[5:] == -1).all()


def test_scc_condense():
    n = 8
    adj = np.zeros((n, n), dtype=np.int8)
    # cycle {0,1,2}; 3 -> cycle; 4 -> 3; 5 independent; 6,7 inactive
    adj[0, 1] = adj[1, 2] = adj[2, 0] = 1
    adj[3, 0] = 1
    adj[4, 3] = 1
    active = np.ones(n, dtype=bool)
    active[6:] = False
    labels, level = ops.scc_condense(jnp.asarray(adj), jnp.asarray(active))
    labels, level = np.asarray(labels), np.asarray(level)
    assert labels[0] == labels[1] == labels[2] == 0
    assert len({labels[3], labels[4], labels[5], 0}) == 4
    assert (labels[6:] == -1).all()
    assert level[0] == level[1] == level[2] == 0
    assert level[3] == 1 and level[4] == 2 and level[5] == 0
    assert (level[6:] == -1).all()


def test_graph_state_insert_evict(nprng):
    st = ops.init_state(16, 8)
    slots = jnp.asarray([0, 3, 7], dtype=jnp.int32)
    key_inc = jnp.asarray(nprng.integers(0, 2, (3, 8)), dtype=jnp.int8)
    ts = jnp.asarray(nprng.integers(1, 100, (3, gs.TS_LANES)), dtype=jnp.int32)
    kind = jnp.asarray([1, 1, 0], dtype=jnp.int8)
    status = jnp.full((3,), gs.PREACCEPTED, dtype=jnp.int8)
    deps = jnp.zeros((3, 16), dtype=jnp.int8)
    st = ops.insert_batch(st, slots, key_inc, ts, ts, kind, status, deps)
    assert bool(st.active[0]) and bool(st.active[3]) and bool(st.active[7])
    assert not bool(st.active[1])
    st = ops.set_status_batch(st, slots, jnp.full((3,), gs.APPLIED, jnp.int8))
    assert int(st.status[3]) == gs.APPLIED
    keep = jnp.ones((16,), dtype=jnp.bool_).at[3].set(False)
    st = ops.evict_mask(st, keep)
    assert not bool(st.active[3])
    assert int(st.status[3]) == 0 and int(st.ts[3, 0]) == 0


def test_consult_packed_matches_consult():
    """Bit-packed consult output unpacks to exactly the boolean mask."""
    import numpy as np
    import jax.numpy as jnp
    from cassandra_accord_tpu.ops import deps_kernels as dk
    rng = np.random.default_rng(3)
    t, k, b = 64, 16, 8
    args = (
        (rng.random((t, k)) < 0.3).astype(np.int8),
        (rng.random((t, k)) < 0.4).astype(np.int8),
        rng.integers(0, 100, (t, 5)).astype(np.int32),
        rng.integers(0, 100, (t, 5)).astype(np.int32),
        rng.integers(0, 2, t).astype(np.int8),
        rng.integers(0, 7, t).astype(np.int8),
        (rng.random(t) < 0.9),
        (rng.random((b, k)) < 0.3).astype(np.int8),
        np.full((b, 5), 50, dtype=np.int32),
        rng.integers(0, 2, b).astype(np.int8),
    )
    jargs = tuple(jnp.asarray(a) for a in args)
    deps, mx = dk.consult(*jargs)
    packed, mx2 = dk.consult_packed(*jargs)
    unpacked = np.unpackbits(np.asarray(packed), axis=1,
                             bitorder="little").astype(bool)[:, :t]
    assert (unpacked == np.asarray(deps)).all()
    assert (np.asarray(mx) == np.asarray(mx2)).all()


# ---------------------------------------------------------------------------
# Frontier tier (ops.frontier_kernels): bit-identity vs the dense tier
# ---------------------------------------------------------------------------

def _random_graph(rng, n, p, shape):
    """Randomized adjacency in one of the adversarial shapes: cyclic (raw),
    DAG (lower-triangular), or cyclic-with-self-loops."""
    adj = (rng.random((n, n)) < p).astype(np.int8)
    if shape == "dag":
        adj = np.tril(adj, k=-1)
    elif shape == "cyclic":
        np.fill_diagonal(adj, 0)
    return adj   # "selfloops": diagonal kept as drawn


def test_frontier_tier_bit_identity(nprng):
    """Every frontier-tier kernel must agree bit-for-bit with its dense twin
    on randomized graphs — cycles, DAGs, self-loops, inactive slots.  This
    is the cross-check-tier contract (the dense kernels stay in-tree exactly
    for this, the way consult keeps its host fallback)."""
    from cassandra_accord_tpu.ops import frontier_kernels as fk
    for trial in range(8):
        n = int(nprng.integers(2, 128))
        p = float(nprng.uniform(0.01, 0.3))
        shape = ("cyclic", "dag", "selfloops")[trial % 3]
        adj = _random_graph(nprng, n, p, shape)
        active = nprng.random(n) < 0.9
        status = np.full(n, gs.STABLE, dtype=np.int8)
        status[nprng.random(n) < 0.3] = gs.APPLIED
        status[nprng.random(n) < 0.1] = gs.INVALIDATED

        dense = np.asarray(ops.transitive_closure(jnp.asarray(adj)))
        assert (dense == fk.transitive_closure_csr(adj)).all(), (trial, shape)

        dense = np.asarray(ops.elide(jnp.asarray(adj)))
        assert (dense == fk.elide_csr(adj)).all(), (trial, shape)

        dl, dv = ops.scc_condense(jnp.asarray(adj), jnp.asarray(active))
        fl, fv = fk.scc_condense_csr(adj, active)
        assert (np.asarray(dl) == fl).all(), (trial, shape)
        assert (np.asarray(dv) == fv).all(), (trial, shape)

        dense = np.asarray(ops.kahn_levels(jnp.asarray(adj),
                                           jnp.asarray(active)))
        assert (dense == fk.kahn_levels_csr(adj, active)).all(), (trial, shape)

        dense = np.asarray(ops.kahn_frontier(
            jnp.asarray(adj), jnp.asarray(status), jnp.asarray(active)))
        assert (dense == fk.kahn_frontier_csr(adj, status,
                                              active)).all(), (trial, shape)


def test_closure_condensed_is_the_dense_view(nprng):
    """``closure_condensed`` (the decision-bearing form the 8k-scale path
    reads) expands to exactly ``transitive_closure_csr``'s dense matrix."""
    from cassandra_accord_tpu.ops import frontier_kernels as fk
    n = 96
    adj = _random_graph(nprng, n, 0.06, "cyclic")
    node_comp, reach_p, nontrivial, c = fk.closure_condensed(adj)
    comp_reach = fk._unpack_cols(reach_p, c)
    comp_reach[np.arange(c), np.arange(c)] |= nontrivial
    dense = comp_reach[np.ix_(node_comp, node_comp)]
    assert (dense == fk.transitive_closure_csr(adj)).all()
    assert (dense == np.asarray(ops.transitive_closure(jnp.asarray(adj)))).all()


def test_frontier_ready_from_edges_matches_dense(nprng):
    """The command-store release path's CSR entry (edge arrays in, ready
    mask out) vs the dense kahn_frontier over the equivalent adjacency."""
    from cassandra_accord_tpu.ops import frontier_kernels as fk
    for _ in range(6):
        n = int(nprng.integers(1, 64))
        e = int(nprng.integers(0, 4 * n))
        src = nprng.integers(0, n, e).astype(np.int32)
        dst = nprng.integers(0, n, e).astype(np.int32)
        status = np.full(n, gs.STABLE, dtype=np.int8)
        status[nprng.random(n) < 0.4] = gs.APPLIED
        active = nprng.random(n) < 0.9
        adj = np.zeros((n, n), dtype=np.int8)
        adj[src, dst] = 1
        want = np.asarray(ops.kahn_frontier(
            jnp.asarray(adj), jnp.asarray(status), jnp.asarray(active)))
        got = fk.frontier_ready_from_edges(src, dst, status, active)
        assert (want == got).all()


def test_evict_slot_reuse_never_resurrects_edges(nprng):
    """Satellite audit (the adjacent-bug shape of the round-12 mirror leak):
    device GraphState eviction + slot reallocation must never leak a stale
    edge into a fresh txn's frontier.  Randomized evict/reinsert cycles are
    checked field-exactly against a host model rebuilt from scratch each
    round — any surviving row/column of an evicted slot, or any edge onto a
    recycled slot's previous occupant, diverges the frontier."""
    t, k = 24, 8
    st = ops.init_state(t, k)
    model_adj = np.zeros((t, t), dtype=np.int8)
    model_active = np.zeros(t, dtype=bool)
    model_status = np.zeros(t, dtype=np.int8)
    free = list(range(t))
    occupied = []
    for rnd in range(12):
        # insert a batch into (possibly recycled) free slots
        nb = int(nprng.integers(1, min(6, len(free)) + 1))
        slots = [free.pop(0) for _ in range(nb)]
        occupied.extend(slots)
        deps = np.zeros((nb, t), dtype=np.int8)
        for i in range(nb):
            # new txns may depend on any currently-occupied slot
            for s in occupied:
                if s not in slots[i:] and nprng.random() < 0.3:
                    deps[i, s] = 1
        key_inc = (nprng.random((nb, k)) < 0.4).astype(np.int8)
        ts = nprng.integers(1, 1000, (nb, gs.TS_LANES)).astype(np.int32)
        status = np.full(nb, gs.STABLE, dtype=np.int8)
        st = ops.insert_batch(st, jnp.asarray(np.asarray(slots, np.int32)),
                              jnp.asarray(key_inc), jnp.asarray(ts),
                              jnp.asarray(ts),
                              jnp.asarray(np.ones(nb, np.int8)),
                              jnp.asarray(status), jnp.asarray(deps))
        model_adj[slots] = deps
        model_active[slots] = True
        model_status[slots] = gs.STABLE
        # apply + evict a random subset of occupied slots
        done = [s for s in occupied if nprng.random() < 0.4]
        if done:
            st = ops.set_status_batch(
                st, jnp.asarray(np.asarray(done, np.int32)),
                jnp.full((len(done),), gs.APPLIED, jnp.int8))
            model_status[done] = gs.APPLIED
            keep = np.ones(t, dtype=bool)
            keep[done] = False
            st = ops.evict_mask(st, jnp.asarray(keep))
            # the model of CORRECT eviction: row, column, and metadata gone
            model_adj[done, :] = 0
            model_adj[:, done] = 0
            model_active[done] = False
            model_status[done] = 0
            for s in done:
                occupied.remove(s)
                free.append(s)
        # field-exact: no stale edge may survive into any future frontier
        assert (np.asarray(st.adj) == model_adj).all(), f"round {rnd}"
        assert (np.asarray(st.active) == model_active).all(), f"round {rnd}"
        got = np.asarray(ops.kahn_frontier(st.adj, st.status, st.active))
        want = np.asarray(ops.kahn_frontier(
            jnp.asarray(model_adj), jnp.asarray(model_status),
            jnp.asarray(model_active)))
        assert (got == want).all(), f"round {rnd}: stale edge in frontier"
        # and the CSR ingress view of the same state (GraphState.adj_edges ->
        # the frontier tier) agrees — the production release path's shape
        from cassandra_accord_tpu.ops import frontier_kernels as fk
        src, dst = ops.adj_edges(st)
        csr = fk.frontier_ready_from_edges(src, dst,
                                           np.asarray(st.status),
                                           np.asarray(st.active))
        assert (csr == got).all(), f"round {rnd}: CSR/dense frontier split"
