"""LatestDeps — phase-aware per-range recovery deps merge (LatestDeps.java),
and the GetDeps/CollectDeps round that fills insufficient footprints.
"""
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.primitives.deps import Deps, KeyDeps
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.latest_deps import (KnownDeps, LatestDeps,
                                                         LatestEntry)
from cassandra_accord_tpu.primitives.timestamp import (Ballot, Domain, Timestamp,
                                                       TxnId, TxnKind)
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def rk(v):
    return IntKey(v).to_routing()


def tid(hlc, node=1):
    return TxnId(epoch=1, hlc=hlc, node=node, kind=TxnKind.WRITE, domain=Domain.KEY)


def ballot(hlc):
    return Ballot(1, hlc, 1)


def deps_of(*pairs):
    return Deps(key_deps=KeyDeps.of({rk(kv): ids for kv, ids in pairs}))


def rngs(lo, hi):
    return Ranges.of(Range(k(lo), k(hi)))


def test_higher_phase_wins_over_union():
    """A STABLE range's decided deps must NOT be polluted by another replica's
    fresh local calculation (which may contain later txns)."""
    decided = deps_of((5, [tid(10)]))
    fresh = deps_of((5, [tid(10), tid(99)]))   # saw a later txn locally
    a = LatestDeps.create(rngs(0, 100), KnownDeps.KNOWN, ballot(1), decided, None)
    b = LatestDeps.create(rngs(0, 100), KnownDeps.UNKNOWN, Ballot.ZERO, None, fresh)
    for merged in (a.merge(b), b.merge(a)):
        deps, sufficient = merged.merge_commit(tid(20), Timestamp(1, 30, 1))
        assert deps.txn_ids() == [tid(10)]     # tid(99) excluded
        assert sufficient.contains(rk(5))


def test_proposal_ballot_tiebreak_excludes_superseded():
    """Two Accept-phase proposals: only the max-ballot one feeds a recovery
    re-proposal (Paxos value adoption, not a union)."""
    old = deps_of((5, [tid(1)]))
    new = deps_of((5, [tid(2)]))
    a = LatestDeps.create(rngs(0, 100), KnownDeps.PROPOSED, ballot(1), old, None)
    b = LatestDeps.create(rngs(0, 100), KnownDeps.PROPOSED, ballot(2), new, None)
    for merged in (a.merge(b), b.merge(a)):
        assert merged.merge_proposal().txn_ids() == [tid(2)]


def test_unknown_unions_locals():
    a = LatestDeps.create(rngs(0, 100), KnownDeps.UNKNOWN, Ballot.ZERO, None,
                          deps_of((5, [tid(1)])))
    b = LatestDeps.create(rngs(0, 100), KnownDeps.UNKNOWN, Ballot.ZERO, None,
                          deps_of((5, [tid(2)])))
    assert set(a.merge(b).merge_proposal().txn_ids()) == {tid(1), tid(2)}


def test_per_range_independence():
    """Phases merge per range: a KNOWN range and an UNKNOWN range from
    different replicas keep their own treatment."""
    a = LatestDeps.create(rngs(0, 50), KnownDeps.KNOWN, ballot(1),
                          deps_of((5, [tid(1)])), None)
    b = LatestDeps.create(rngs(50, 100), KnownDeps.UNKNOWN, Ballot.ZERO, None,
                          deps_of((60, [tid(2)])))
    merged = a.merge(b)
    # slow path (executeAt != txnId): only the KNOWN range is sufficient
    deps, sufficient = merged.merge_commit(tid(20), Timestamp(1, 30, 2))
    assert deps.txn_ids() == [tid(1)]
    assert sufficient.contains(rk(5)) and not sufficient.contains(rk(60))
    # fast path: the UNKNOWN range's locals become usable
    deps, sufficient = merged.merge_commit(tid(20), tid(20).as_timestamp())
    assert set(deps.txn_ids()) == {tid(1), tid(2)}
    assert sufficient.contains(rk(60))


def test_deps_sliced_to_their_range():
    """An entry spanning a sub-interval only contributes deps inside it."""
    wide = deps_of((5, [tid(1)]), (80, [tid(2)]))
    a = LatestDeps.create(rngs(0, 100), KnownDeps.KNOWN, ballot(1), wide, None)
    # a competing higher-phase claim on [50, 100) hides the [50,100) slice of a
    b = LatestDeps.create(rngs(50, 100), KnownDeps.KNOWN, ballot(9),
                          deps_of((80, [tid(3)])), None)
    merged = LatestDeps.merge_all([a, b])
    deps, _ = merged.merge_commit(tid(20), Timestamp(1, 30, 2))
    got = set(deps.txn_ids())
    assert tid(1) in got
    # [80] comes from whichever entry won [50,100); both are KNOWN so the
    # winner is deterministic by reduce order — what matters is no union
    assert not (tid(2) in got and tid(3) in got)


def test_get_deps_round_end_to_end():
    """CollectDeps: a GetDeps quorum returns the conflicting txns for a
    footprint at a bound."""
    from cassandra_accord_tpu.coordinate.collect_deps import collect_deps
    from cassandra_accord_tpu.primitives.keys import RoutingKeys
    from cassandra_accord_tpu.primitives.route import Route
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=11)
    results = [cluster.nodes[1].coordinate(list_txn([k(5)], {k(5): f"v{i}"}))
               for i in range(3)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    node = cluster.nodes[2]
    probe = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    route = Route.for_keys(rk(5), RoutingKeys.of([rk(5)]))
    got = collect_deps(node, probe, route, [k(5)],
                       node.unique_now())
    assert cluster.run_until(lambda: got.is_done())
    assert got.failure is None
    assert len(got.value.txn_ids()) >= 1   # the committed writes conflict
