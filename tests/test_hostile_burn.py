"""The hostile burn: randomized message loss, failures, latency spikes and
minority partitions re-rolled every 5s of sim-time, with recovery driving every
op to a resolution.

Parity targets: the reference burn's chaos configuration
(accord/impl/basic/Cluster.java:455-459 link re-randomization + partitions,
NodeSink.java:45 action set), client lost-response resolution via home-shard
CheckStatus probes (impl/list/ListRequest.java:61-150), scheduled durability +
truncation running during the burn (Cluster.java:429-445), and the
reconciling double-run (BurnTest.reconcile).

Every op must resolve as acked / recovered / invalidated / lost; acked and
recovered ops are fully verified for strict serializability, invalidated ops'
writes must never surface, and the final replica states must agree.
"""
import pytest

from cassandra_accord_tpu.harness.burn import SimulationException, reconcile, run_burn

HOSTILE = dict(ops=60, concurrency=10, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)


@pytest.mark.parametrize("seed", [1, 2, 4, 7, 12, 17])
def test_hostile_burn(seed):
    """Full fault matrix: drops+failures+latency spikes+partitions, scheduled
    durability/truncation, delayed stores, clock drift, journal replay."""
    result = run_burn(seed, **HOSTILE)
    assert result.resolved == HOSTILE["ops"]
    assert result.ops_failed == 0


def test_hostile_burn_with_topology_churn():
    """Chaos + randomized topology mutations (split/merge/move + bootstrap)."""
    for seed in (1, 2):
        result = run_burn(seed, ops=60, concurrency=10, chaos=True,
                          allow_failures=True, topology_churn=True,
                          durability=True, journal=True, max_tasks=3_000_000)
        assert result.resolved == 60


def test_hostile_burn_is_deterministic():
    """Same seed, same chaos, same outcome — the fault pattern replays
    (BurnTest.reconcile / ReconcilingLogger)."""
    reconcile(3, **HOSTILE)


def test_chaos_without_recovery_stalls():
    """The faults must BITE: with the progress log (recovery driver) disabled,
    the same chaos config fails — ops stall unresolved or fail outright —
    proving the hostile matrix exercises the recovery machinery."""
    with pytest.raises(SimulationException):
        run_burn(4, ops=60, concurrency=10, chaos=True, allow_failures=False,
                 progress_log=False, max_tasks=1_000_000)


def test_hostile_burn_verifies_resolver_parity(monkeypatch):
    """Hostile burn with the verify resolver: every deps query answered by both
    the CPU walk and the TPU data plane, asserted equal."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")   # exercise vector tiers
    result = run_burn(5, ops=40, concurrency=8, chaos=True, allow_failures=True,
                      durability=True, resolver="verify", max_tasks=3_000_000)
    assert result.resolved == 40


def test_hostile_burn_with_cache_misses():
    """Full fault matrix PLUS cache-miss injection: terminal commands keep
    getting evicted, so recovery/evidence/GC paths run against state that
    must fault back in from the journal (PreLoadContext capability)."""
    result = run_burn(21, ops=60, concurrency=10, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      delayed_stores=True, cache_miss=True,
                      max_tasks=3_000_000)
    assert result.resolved == 60
    assert result.stats.get("cache_miss_loads", 0) > 0, \
        "eviction never forced a reload — the injection is not biting"


def test_benign_burn_with_cache_misses_verify_resolver(monkeypatch):
    """Cache misses under the parity-asserting resolver and journal verify:
    reloads must leave every data plane consistent."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(22, ops=80, concurrency=8, journal=True,
                      cache_miss=True, resolver="verify")
    assert result.ops_ok == 80
    assert result.stats.get("cache_miss_loads", 0) > 0


@pytest.mark.skipif("ACCORD_LONG_BURNS" not in __import__("os").environ,
                    reason="~5 min; run with ACCORD_LONG_BURNS=1")
def test_hostile_burn_seed_112_superseding_race_regression():
    """KNOWN_ISSUES.md: the superseding race — recovery completing the fast
    path while a later-started conflict had fast-committed around us.  Fixed
    by the later-unknown-witness wait; this seed reproduced all three
    variants of the race family during round 3."""
    run_burn(112, ops=1000, concurrency=20, chaos=True, allow_failures=True,
             durability=True, journal=True, delayed_stores=True,
             clock_drift=True, cache_miss=True, max_tasks=200_000_000)
