"""Columnar protocol engine (protocol_batch/): the exact-skip proof chain.

The engine's contract is that ``columnar=on`` NEVER changes a protocol
decision — every vectorized pass either answers a pure read bit-identically
or skips scalar work it can prove is a no-op.  Proven here at three levels:

1. end-to-end: same-seed hostile burn columnar on-vs-off is byte-identical
   (full message trace + audit verdict + outcome partition) — extending the
   PR 3/8/10 zero-observer-effect proof chain to the engine;
2. per-pass property tests: the release skip mask and the frontier
   still-blocks mask agree with the REAL scalar predicates over randomized
   command states; the ragged ConsultBatch bridge round-trips empty /
   duplicate / max-width rows against a scalar densify;
3. the ramp smoke: protocol commits per SIM second strictly increases
   across two in-flight levels (the ROADMAP item-1 scaling oracle, on the
   deterministic sim plane so it can gate in tier-1).
"""
import numpy as np
import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.local.command import Command
from cassandra_accord_tpu.local.commands import _still_blocks
from cassandra_accord_tpu.local.status import SaveStatus
from cassandra_accord_tpu.primitives.timestamp import (Domain, Timestamp,
                                                       TxnId, TxnKind)
from cassandra_accord_tpu.protocol_batch import (BatchEngine, TxnBatch,
                                                 columnar_enabled,
                                                 pack_order_lanes)
from cassandra_accord_tpu.utils.random import RandomSource

# concurrency 24 + few keys: deps lists and listener fan-outs cross the
# engine's >=16 engagement floor, so the identity proof exercises the
# vectorized passes for real (asserted below via the columnar_* counters)
HOSTILE = dict(ops=60, concurrency=24, key_count=5, chaos=True,
               allow_failures=True, durability=True, journal=True,
               delayed_stores=True, clock_drift=True, audit="warn",
               max_tasks=5_000_000)

# tier-choice counters are wall-clock driven (excluded from the determinism
# contract, as in reconcile); columnar_* exist only when the engine is on
_EXCLUDED_STAT_PREFIXES = ("resolver_host_consults", "resolver_native_",
                           "resolver_device_", "resolver_service_",
                           "columnar_")


def _comparable_stats(stats):
    return {k: v for k, v in stats.items()
            if not k.startswith(_EXCLUDED_STAT_PREFIXES)}


# ---------------------------------------------------------------------------
# 1. end-to-end byte-identity
# ---------------------------------------------------------------------------

def test_columnar_on_off_hostile_byte_identity():
    """Same-seed hostile burn columnar on vs off: identical full message
    traces, identical audit verdicts, identical outcome partitions — the
    knob buys wall-clock, never trajectory."""
    ta, tb = Trace(), Trace()
    off = run_burn(11, tracer=ta.hook, columnar="off", **HOSTILE)
    on = run_burn(11, tracer=tb.hook, columnar="on", **HOSTILE)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"columnar engine perturbed the simulation:\n{divergence}"
    assert (off.ops_ok, off.ops_recovered, off.ops_nacked, off.ops_lost,
            off.ops_failed, off.sim_micros) == \
           (on.ops_ok, on.ops_recovered, on.ops_nacked, on.ops_lost,
            on.ops_failed, on.sim_micros)
    assert _comparable_stats(off.stats) == _comparable_stats(on.stats)
    # audit verdicts identical (violations, SLO flags — the strict oracles
    # would judge both runs the same)
    assert off.audit is not None and on.audit is not None
    assert off.audit == on.audit
    # and the engine actually engaged (otherwise this test proves nothing)
    assert on.stats.get("columnar_release_scans", 0) \
        + on.stats.get("columnar_frontier_scans", 0) \
        + on.stats.get("columnar_poll_scans", 0) > 0
    assert "columnar_release_scans" not in off.stats


def test_columnar_on_off_benign_byte_identity():
    kw = dict(ops=60, concurrency=16, nodes=3, rf=3, key_count=4,
              durability=True, journal=True)
    ta, tb = Trace(), Trace()
    off = run_burn(5, tracer=ta.hook, columnar="off", **kw)
    on = run_burn(5, tracer=tb.hook, columnar="on", **kw)
    assert diff_traces(ta, tb) is None
    assert off.sim_micros == on.sim_micros
    assert off.ops_ok == on.ops_ok


# ---------------------------------------------------------------------------
# 2. per-pass property tests
# ---------------------------------------------------------------------------

class _FakeStore:
    """The slice of CommandStore the engine + _still_blocks read."""

    def __init__(self):
        self.cold = set()
        self.commands = {}
        self.batch_engine = None


class _FakeSafe:
    def __init__(self, store):
        self.store = store

    def get_if_exists(self, txn_id):
        return self.store.commands.get(txn_id)


def _tid(rng, kind=None):
    kind = kind if kind is not None else rng.pick(
        [TxnKind.READ, TxnKind.WRITE, TxnKind.WRITE,
         TxnKind.EXCLUSIVE_SYNC_POINT])
    return TxnId(1, 1000 + rng.next_int(100000), 1 + rng.next_int(5),
                 kind, Domain.KEY)


def _random_command(rng, txn_id):
    """A Command in a random lifecycle state, mirrored like the live choke
    point would have (every save_status change runs through the transition
    hook with execute_at already settled)."""
    cmd = Command(txn_id)
    roll = rng.next_float()
    if roll < 0.15:
        pass                                   # NOT_DEFINED stub
    elif roll < 0.3:
        cmd.save_status = SaveStatus.PRE_ACCEPTED
    elif roll < 0.45:
        cmd.execute_at = Timestamp(1, 2000 + rng.next_int(100000),
                                   1 + rng.next_int(5))
        cmd.save_status = SaveStatus.COMMITTED
    elif roll < 0.65:
        cmd.execute_at = Timestamp(1, 2000 + rng.next_int(100000),
                                   1 + rng.next_int(5))
        cmd.save_status = SaveStatus.STABLE
    elif roll < 0.8:
        cmd.execute_at = Timestamp(1, 2000 + rng.next_int(100000),
                                   1 + rng.next_int(5))
        cmd.save_status = SaveStatus.PRE_APPLIED
    elif roll < 0.9:
        cmd.execute_at = Timestamp(1, 2000 + rng.next_int(100000),
                                   1 + rng.next_int(5))
        cmd.save_status = SaveStatus.APPLIED
    else:
        cmd.save_status = SaveStatus.INVALIDATED
    return cmd


def _engine_with(store):
    engine = BatchEngine.__new__(BatchEngine)
    engine.store = store
    engine.batch = TxnBatch()
    engine.stats = {k: 0 for k in
                    ("release_scans", "release_skipped", "release_visited",
                     "poll_scans", "poll_fast", "frontier_scans",
                     "frontier_fast", "ingress_windows", "ingress_rows")}
    engine._key_slots = {}
    return engine


def test_release_skip_mask_matches_scalar():
    """Every waiter the mask skips is PROVABLY a scalar no-op: the real
    ``_still_blocks`` answers True (still blocked) and the waiter is not
    awaits-only (so ``_maybe_defer`` cannot mutate it)."""
    rng = RandomSource(99)
    for _trial in range(200):
        store = _FakeStore()
        safe = _FakeSafe(store)
        engine = _engine_with(store)
        dep = _random_command(rng, _tid(rng))
        store.commands[dep.txn_id] = dep
        engine.note_transition(dep)
        waiters = []
        for _ in range(12):
            w = _random_command(rng, _tid(rng))
            store.commands[w.txn_id] = w
            engine.note_transition(w)
            waiters.append(w.txn_id)
        skip = engine.release_skip_mask(dep, waiters)
        if skip is None:
            continue
        for i, wid in enumerate(waiters):
            if not skip[i]:
                continue
            waiter = store.commands[wid]
            assert not wid.kind.awaits_only_deps
            assert waiter.execute_at is not None
            # the scalar predicate must agree the waiter stays blocked
            assert _still_blocks(safe, waiter, dep.txn_id,
                                 waiter.execute_at) is True


def test_still_blocks_mask_matches_scalar():
    """Wherever the frontier mask claims a decided answer, the real scalar
    ``_still_blocks`` answers identically."""
    rng = RandomSource(7)
    for _trial in range(200):
        store = _FakeStore()
        safe = _FakeSafe(store)
        engine = _engine_with(store)
        dep_ids = []
        for _ in range(16):
            d = _random_command(rng, _tid(rng))
            if rng.next_float() < 0.8:
                store.commands[d.txn_id] = d
                engine.note_transition(d)
            # else: unmirrored (cold/unwitnessed stand-in) — must be
            # undecided by the mask
            dep_ids.append(d.txn_id)
        execute_at = Timestamp(1, 2000 + rng.next_int(100000), 1)
        waiter = Command(_tid(rng, TxnKind.WRITE))
        waiter.execute_at = execute_at
        blocks, decided = engine.still_blocks_mask(dep_ids, execute_at,
                                                   awaits_only=False)
        for i, dep_id in enumerate(dep_ids):
            if not decided[i]:
                continue
            assert bool(blocks[i]) == _still_blocks(safe, waiter, dep_id,
                                                    execute_at)


def test_settled_partition_matches_store():
    rng = RandomSource(3)
    store = _FakeStore()
    engine = _engine_with(store)
    ids = []
    for _ in range(64):
        cmd = _random_command(rng, _tid(rng))
        if rng.next_float() < 0.7:
            store.commands[cmd.txn_id] = cmd
            engine.note_transition(cmd)
        ids.append(cmd.txn_id)
    done, outcome, resident = engine.settled_partition(ids)
    for i, tid in enumerate(ids):
        cmd = store.commands.get(tid)
        if resident[i]:
            assert cmd is not None
            assert bool(done[i]) == (cmd.save_status.ordinal
                                     >= SaveStatus.APPLIED.ordinal)
            assert bool(outcome[i]) == (cmd.save_status.ordinal
                                        >= SaveStatus.PRE_APPLIED.ordinal)
        # non-resident rows carry no claims (scalar path handles them)


def test_consult_batch_bridge_ragged_rows():
    """Empty rows, duplicate columns, and max-width rows all round-trip the
    TxnBatch -> ConsultBatch ingress bridge; the txn_rows attribution lanes
    carry the canonical pack_lanes of each querying txn."""
    batch = TxnBatch()
    rng = RandomSource(21)
    ids = [_tid(rng, TxnKind.WRITE) for _ in range(5)]
    key_sets = [
        (),                           # empty row (legal: width 0)
        (3, 3, 3),                    # duplicate columns collapse in densify
        tuple(range(16)),             # max-width row
        (1,),
        (2, 5),
    ]
    for tid, cols in zip(ids, key_sets):
        batch.ensure(tid)
        batch.set_keys(tid, cols)
    before = [Timestamp(1, 50_000 + i, 1).pack_lanes()
              for i in range(len(ids))]
    kinds = [int(t.kind) for t in ids]
    cb = batch.to_consult_batch(ids, before, kinds)
    # pow2 bucket shape discipline (the jit-stability contract)
    rows_bucket, flat_bucket = cb.shape_signature
    assert rows_bucket & (rows_bucket - 1) == 0
    assert flat_bucket & (flat_bucket - 1) == 0
    assert cb.rows == len(ids)
    # offsets describe exactly the ragged rows
    widths = [cb.offsets[i + 1] - cb.offsets[i] for i in range(cb.rows)]
    assert widths == [len(c) for c in key_sets]
    # densify == scalar expectation (duplicates collapse to 1)
    dense = cb.densify(k=16)
    expect = np.zeros((len(ids), 16), dtype=np.int8)
    for i, cols in enumerate(key_sets):
        for c in cols:
            expect[i, c] = 1
    assert (dense == expect).all()
    # txn_rows: the previously-reserved attribution lanes are populated
    for i, tid in enumerate(ids):
        assert tuple(int(v) for v in cb.txn_rows[i]) == tid.pack_lanes()
    # padding rows are width-0 and carry zero txn lanes
    for i in range(cb.rows, rows_bucket):
        assert cb.offsets[i + 1] == cb.offsets[i]
        assert not cb.txn_rows[i].any()


def test_consult_ingress_from_query_specs():
    """The engine packs a delivery window's resolver QuerySpecs into one
    ragged ConsultBatch with querying-txn attribution — the ingress path the
    delivery-window coalescing feeds."""
    from cassandra_accord_tpu.impl.resolver import QuerySpec
    from cassandra_accord_tpu.primitives.keys import IntKey
    rng = RandomSource(13)
    store = _FakeStore()
    engine = _engine_with(store)
    keys = [IntKey(i * 10).to_routing() for i in range(6)]
    specs = []
    for i in range(5):
        by = _tid(rng, TxnKind.WRITE)
        specs.append(QuerySpec("kc", by, keys[: 1 + i % 3],
                               Timestamp(1, 90_000 + i, 1)))
    cb = engine.consult_ingress(specs, engine.key_slot)
    assert cb.rows == len(specs)
    for i, spec in enumerate(specs):
        lo, hi = int(cb.offsets[i]), int(cb.offsets[i + 1])
        assert hi - lo == len(spec.keys)
        assert tuple(int(v) for v in cb.txn_rows[i]) == spec.by.pack_lanes()
    # key slots are stable across windows (first-witness order)
    assert engine.key_slot(keys[0]) == 0


def test_order_lanes_agree_with_timestamp_order():
    rng = RandomSource(17)
    ts = [Timestamp(1 + rng.next_int(3), rng.next_int(1 << 40),
                    rng.next_int(32), flags=rng.next_int(4))
          for _ in range(200)]
    import numpy as _np
    lanes = _np.array([pack_order_lanes(t) for t in ts], dtype=_np.int64)
    from cassandra_accord_tpu.protocol_batch.columns import lanes_le, lanes_lt
    bound = ts[0]
    lt = lanes_lt(lanes, pack_order_lanes(bound))
    le = lanes_le(lanes, pack_order_lanes(bound))
    for i, t in enumerate(ts):
        assert bool(lt[i]) == (t < bound)
        assert bool(le[i]) == (t <= bound)


def test_columnar_knob_resolution():
    from dataclasses import replace

    from cassandra_accord_tpu.config import LocalConfig
    assert columnar_enabled(replace(LocalConfig(), columnar="auto"))
    assert columnar_enabled(replace(LocalConfig(), columnar="on"))
    assert not columnar_enabled(replace(LocalConfig(), columnar="off"))
    with pytest.raises(ValueError):
        columnar_enabled(replace(LocalConfig(), columnar="maybe"))


def test_cfk_merged_walk_cache_consistency():
    """The memoized cold+hot merged order always equals a fresh sort after
    arbitrary mutation sequences (membership changes must invalidate)."""
    from cassandra_accord_tpu.local.cfk import CommandsForKey, InternalStatus
    from cassandra_accord_tpu.primitives.keys import IntKey
    rng = RandomSource(31)
    cfk = CommandsForKey(IntKey(1).to_routing())
    known = []
    for step in range(400):
        roll = rng.next_float()
        if roll < 0.5 or not known:
            tid = _tid(rng, TxnKind.WRITE)
            ea = Timestamp(1, tid.hlc + rng.next_int(50), tid.node)
            cfk.update(tid, InternalStatus.PREACCEPTED)
            known.append((tid, ea))
        elif roll < 0.8:
            tid, ea = known[rng.next_int(len(known))]
            status = rng.pick([InternalStatus.COMMITTED, InternalStatus.STABLE,
                               InternalStatus.APPLIED])
            cfk.update(tid, status, ea)
            if rng.next_float() < 0.5:
                cfk.mark_durable(tid)
        else:
            tid, _ea = known[rng.next_int(len(known))]
            cfk.prune_applied_before(tid)
        if step % 10 == 0:
            # force the merged walk (sync-point query: flag_elision False)
            seen = []
            cfk.map_reduce_active(Timestamp.MAX, lambda _t: True, seen.append,
                                  flag_elision=False)
            if cfk._merged_cache is not None:
                fresh = sorted(list(cfk.cold.values()) + cfk.by_id)
                assert [e.txn_id for e in cfk._merged_cache] \
                    == [e.txn_id for e in fresh]


def test_deps_memo_roundtrip():
    """The Deps lazy memo (txn_ids/participants) returns stable answers and
    survives the wire codec (the _memo slot never hits the wire)."""
    from cassandra_accord_tpu.maelstrom.codec import (_register_all,
                                                      decode_value,
                                                      encode_value)
    from cassandra_accord_tpu.primitives.deps import DepsBuilder
    from cassandra_accord_tpu.primitives.keys import IntKey
    _register_all()
    rng = RandomSource(41)
    b = DepsBuilder()
    tids = [_tid(rng, TxnKind.WRITE) for _ in range(8)]
    for i, tid in enumerate(tids):
        b.add(IntKey(i % 3).to_routing(), tid)
    deps = b.build()
    first = deps.txn_ids()
    assert deps.txn_ids() is first          # memoized
    keys0, rngs0 = deps.participants(tids[0])
    assert deps.participants(tids[0]) == (keys0, rngs0)
    back = decode_value(encode_value(deps))
    assert back.txn_ids() == first          # recomputed post-decode, equal


# ---------------------------------------------------------------------------
# 3. the concurrency-ramp smoke (deterministic sim plane)
# ---------------------------------------------------------------------------

def test_protocol_ramp_sim_rate_increases():
    """Commits per SIM second strictly increases across two in-flight
    levels — the protocol-level scaling oracle (ROADMAP item 1: the rate
    must scale with concurrency, not flatline).  Sim-time, so deterministic:
    no wall-clock flake."""
    kw = dict(ops=120, concurrency=None, nodes=3, rf=3, key_count=6,
              durability=True, journal=True)
    rates = []
    for conc in (4, 24):
        kw["concurrency"] = conc
        res = run_burn(seed=7, **kw)
        assert res.ops_ok == 120
        rates.append(res.ops_ok / (res.sim_micros / 1e6))
    assert rates[1] > rates[0], \
        f"protocol commits/s flatlined across the ramp: {rates}"
