"""Harness fidelity: journal replay, delayed stores, clock drift, reconcile.

Parity targets: impl/basic/Journal.java (diff log + reconstruct),
DelayedCommandStores.java:138-195 (random store-task delay),
BurnTest.java:329-339 (clock drift), BurnTest.reconcile / ReconcilingLogger.
"""
import pytest

from cassandra_accord_tpu.harness.burn import reconcile, run_burn
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def make_cluster(seed=1, **kw):
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    return Cluster(Topology(1, shards), seed=seed, **kw)


def test_journal_reconstructs_store_state():
    cluster = make_cluster(seed=3, journal=True)
    results = [cluster.nodes[1 + (i % 3)].coordinate(
        list_txn([k(5)] if i % 2 else [], {k(i * 7 % 100): f"v{i}"}))
        for i in range(10)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    assert cluster.journal.records > 0
    for node in cluster.nodes.values():
        for store in node.command_stores.all_stores():
            cluster.journal.verify_against(store)
    # reconstruction is a faithful copy, not a reference to live state
    any_store = cluster.nodes[1].command_stores.all_stores()[0]
    rebuilt = cluster.journal.reconstruct(1, any_store.id)
    for txn_id, cmd in rebuilt.items():
        live = any_store.commands[txn_id]
        assert cmd is not live
        assert cmd.save_status is live.save_status


def test_journal_diffs_are_incremental():
    cluster = make_cluster(seed=5, journal=True)
    r = cluster.nodes[1].coordinate(list_txn([], {k(50): "x"}))
    assert cluster.run_until(r.is_done)
    cluster.run_until_idle()
    store = cluster.nodes[1].command_stores.all_stores()[0]
    logs = cluster.journal.logs[(1, store.id)]
    some_txn = next(iter(logs))
    # records store the diff's canonical JSON + CRC32; decode verifies both
    diffs = [record.diff() for record in logs[some_txn]]
    assert len(diffs) >= 2            # several transitions recorded
    # later diffs must be partial (only changed fields), not full snapshots
    assert any(len(d) < len(diffs[0]) for d in diffs[1:]), diffs


def test_burn_with_delayed_stores():
    for seed in (4, 21):
        res = run_burn(seed, ops=100, concurrency=8, delayed_stores=True)
        assert res.ops_ok == 100, res


def test_burn_with_clock_drift():
    for seed in (6, 33):
        res = run_burn(seed, ops=100, concurrency=8, clock_drift=True)
        assert res.ops_ok == 100, res


def test_burn_all_faults_with_journal():
    res = run_burn(13, ops=80, concurrency=8, delayed_stores=True,
                   clock_drift=True, journal=True, topology_churn=True)
    assert res.ops_ok == 80, res


def test_reconcile_determinism():
    reconcile(9, ops=60, concurrency=6)
    reconcile(9, ops=60, concurrency=6, delayed_stores=True, clock_drift=True)


def test_reconcile_diffs_full_traces():
    """reconcile compares COMPLETE message traces (not summary scalars):
    hostile-config double-runs must produce byte-identical event sequences,
    and an artificial divergence must be pinpointed."""
    from cassandra_accord_tpu.harness.burn import reconcile
    from cassandra_accord_tpu.harness.trace import Trace, diff_traces
    reconcile(777, ops=40, concurrency=6, chaos=True, allow_failures=True,
              durability=True, journal=True, max_tasks=2_000_000)
    # the differ pinpoints the first divergent event
    a, b = Trace(), Trace()
    for i in range(5):
        a.hook("SEND", 1, 2, i, object(), 100 + i)
        b.hook("SEND", 1, 2, i if i != 3 else 99, object(), 100 + i)
    report = diff_traces(a, b)
    assert report is not None and "event 3" in report


def test_serialization_graph_detects_antidependency_cycle():
    """The Elle-core check: a classic rw-antidependency cycle (write-skew
    shape) that passes every per-key prefix / real-time / atomicity check
    must still be rejected."""
    import pytest
    from cassandra_accord_tpu.harness.verifier import (HistoryViolation,
                                                       StrictSerializabilityVerifier)
    from cassandra_accord_tpu.primitives.keys import IntKey
    k1, k2 = IntKey(1), IntKey(2)
    v = StrictSerializabilityVerifier()
    # concurrent ops: A reads k1 empty, writes k2; B reads k2 empty, writes k1
    a = v.begin(0)
    b = v.begin(0)
    a.complete(10, {k1: ()}, {k2: "a"})
    b.complete(10, {k2: ()}, {k1: "b"})
    final = {k1: ("b",), k2: ("a",)}
    with pytest.raises(HistoryViolation, match="cycle"):
        v.verify(final)


def test_serialization_graph_accepts_serializable_history():
    from cassandra_accord_tpu.harness.verifier import StrictSerializabilityVerifier
    from cassandra_accord_tpu.primitives.keys import IntKey
    k1, k2 = IntKey(1), IntKey(2)
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(5, {k1: ()}, {k2: "a"})
    b = v.begin(6)                      # after a completed
    b.complete(9, {k2: ("a",)}, {k1: "b"})
    v.verify({k1: ("b",), k2: ("a",)})
